"""Ragged-batch device lookup plane (ISSUE 18).

Covers the four contracts the arena must keep:
- packing correctness: seeded ragged batches (random segment counts and
  lengths, empty segments, single-probe tails) answered in one dispatch
  agree entry-wise with per-segment host `IndexSnapshot.lookup` AND a
  plain dict oracle;
- double-buffer safety: a probe in flight during a generation swap
  stays byte-identical (generations are immutable; the swap is a
  pointer);
- LRU eviction: a byte budget denies residency to the least-recently
  ensured segments and the arena says so (cold -> host fallback), it
  never serves wrong answers;
- proven host fallback on BOTH dispatch-capable paths: killing the
  arena under the volume lookup gate and under the filer meta gate
  degrades to host lookups with zero identity violations.
"""

import asyncio
import os
import threading

import numpy as np
import pytest

from seaweedfs_tpu.ops.index_kernel import IndexSnapshot
from seaweedfs_tpu.ops.ragged_lookup import (
    ArenaSegment,
    DeviceColumnArena,
)


def _make_segment(rng, n, key_space=1_000_000):
    if n:
        keys = np.sort(
            rng.choice(
                np.arange(1, key_space, dtype=np.uint64),
                size=n,
                replace=False,
            )
        )
    else:
        keys = np.zeros(0, dtype=np.uint64)
    offs = rng.integers(1, 1 << 30, size=n).astype(np.uint32)
    sizes = rng.integers(1, 1 << 20, size=n).astype(np.uint32)
    return ArenaSegment(keys=keys, offs=offs, sizes=sizes)


def _host_answer(segments, key):
    """Newest-first host oracle over raw columns."""
    for rank, s in enumerate(segments):
        i = np.searchsorted(s.keys, np.uint64(key))
        if i < s.count and s.keys[i] == key:
            return rank, int(s.offs[i]), int(s.sizes[i])
    return None


@pytest.mark.parametrize("seed", [0, 7, 23])
def test_ragged_packing_matches_host_and_oracle(seed):
    """Random segment shapes — including EMPTY segments and single-probe
    tail groups — packed into one dispatch agree with per-segment
    IndexSnapshot.lookup and with the dict oracle, entry-wise."""
    rng = np.random.default_rng(seed)
    arena = DeviceColumnArena()
    try:
        groups = []
        for gi in range(5):
            n_segs = int(rng.integers(1, 5))
            sizes_pool = [0, 1, 3, 97, 800, 5000]
            segs = [
                _make_segment(rng, int(rng.choice(sizes_pool)))
                for _ in range(n_segs)
            ]
            # probes: known keys from random segments + guaranteed misses
            # + a single-probe tail group at the end
            known = [
                int(s.keys[rng.integers(0, s.count)])
                for s in segs
                for _ in range(3)
                if s.count
            ]
            misses = rng.integers(
                2_000_000, 3_000_000, size=4, dtype=np.uint64
            ).tolist()
            probes = np.asarray(known + misses, dtype=np.uint64)
            if gi == 4:  # single-probe tail
                probes = probes[:1]
            groups.append((segs, probes))
        for segs, _p in groups:
            arena.ensure(segs)
        arena.refresh_sync()
        results = arena.probe_groups(groups)
        assert all(r is not None for r in results)
        for (segs, probes), res in zip(groups, results):
            # per-segment host snapshots (skip empties: IndexSnapshot
            # requires rows; an empty run can't answer anything anyway)
            snaps = [
                (rank, IndexSnapshot(s.keys, s.offs, s.sizes))
                for rank, s in enumerate(segs)
                if s.count
            ]
            for i, key in enumerate(probes.tolist()):
                want = _host_answer(segs, key)
                got = (
                    (
                        int(res["rank"][i]),
                        int(res["off"][i]),
                        int(res["size"][i]),
                    )
                    if res["found"][i]
                    else None
                )
                assert got == want, (key, got, want)
                # cross-check against the single-table device kernel
                snap_hit = None
                for rank, snap in snaps:
                    o, s_, f = snap.lookup(
                        np.asarray([key], dtype=np.uint64)
                    )
                    if bool(f[0]):
                        snap_hit = (rank, int(o[0]), int(s_[0]))
                        break
                assert snap_hit == want, (key, snap_hit, want)
    finally:
        arena.close()


def test_segment_end_bound_blocks_cross_segment_match():
    """A probe whose own segment lacks the key must NOT match an equal
    key living in the NEXT segment's rows (the `end` bound in
    _search_range_bounded) — the regression the bounded search exists
    for."""
    rng = np.random.default_rng(1)
    shared = np.asarray([500_000], dtype=np.uint64)
    a = ArenaSegment(
        keys=np.asarray([1, 2], dtype=np.uint64),
        offs=np.asarray([11, 12], dtype=np.uint32),
        sizes=np.asarray([1, 1], dtype=np.uint32),
    )
    b = ArenaSegment(
        keys=shared,
        offs=np.asarray([99], dtype=np.uint32),
        sizes=np.asarray([7], dtype=np.uint32),
    )
    arena = DeviceColumnArena()
    try:
        arena.ensure([a, b])
        arena.refresh_sync()
        # group probing ONLY segment a: 500000 must be absent even
        # though segment b (adjacent rows in the arena) holds it
        res = arena.probe_groups([([a], shared)])[0]
        assert res is not None
        assert not res["found"][0]
        # and via both segments it IS found, from b (rank 1)
        res2 = arena.probe_groups([([a, b], shared)])[0]
        assert res2["found"][0] and int(res2["rank"][0]) == 1
        assert int(res2["off"][0]) == 99
    finally:
        arena.close()


def test_refresh_race_probe_stays_byte_identical():
    """Probes racing a double-buffered generation swap return byte-
    identical answers throughout: in-flight dispatches keep their
    reference to the old immutable generation while the new one
    uploads."""
    rng = np.random.default_rng(3)
    arena = DeviceColumnArena()
    try:
        segs = [_make_segment(rng, 3000), _make_segment(rng, 900)]
        probes = np.concatenate(
            [
                segs[0].keys[rng.integers(0, 3000, size=40)],
                rng.integers(2_000_000, 3_000_000, size=8, dtype=np.uint64),
            ]
        )
        arena.ensure(segs)
        arena.refresh_sync()
        baseline = arena.probe_groups([(segs, probes)])[0]
        assert baseline is not None
        errs: list = []
        stop = threading.Event()

        def prober():
            while not stop.is_set():
                res = arena.probe_groups([(segs, probes)])[0]
                if res is None:
                    errs.append("went cold during refresh")
                    return
                for k in ("found", "rank", "off", "size"):
                    if not np.array_equal(res[k], baseline[k]):
                        errs.append(f"{k} diverged during swap")
                        return

        t = threading.Thread(target=prober)
        t.start()
        try:
            # churn generations underneath the prober
            for _ in range(6):
                extra = _make_segment(rng, 1200)
                arena.ensure(segs + [extra])
                arena.refresh_sync()
        finally:
            stop.set()
            t.join(10)
        assert not errs, errs
    finally:
        arena.close()


def test_lru_eviction_under_byte_budget():
    """Segments past the byte budget lose residency least-recently-
    ensured first; probing an evicted set answers None (host fallback),
    never wrong data."""
    rng = np.random.default_rng(5)
    seg_a = _make_segment(rng, 4000)  # 64 KB columns each
    seg_b = _make_segment(rng, 4000)
    budget = seg_a.nbytes + seg_b.nbytes // 2  # fits one, not both
    arena = DeviceColumnArena(budget_bytes=budget)
    try:
        arena.ensure([seg_a])
        arena.refresh_sync()
        assert arena.probe_groups([([seg_a], seg_a.keys[:5])])[0] is not None
        # touch b more recently; the next refresh must evict a
        arena.ensure([seg_b])
        arena.refresh_sync()
        assert arena.counters["evictions"] > 0
        assert arena.probe_groups([([seg_b], seg_b.keys[:5])])[0] is not None
        res_a = arena.probe_groups([([seg_a], seg_a.keys[:5])])[0]
        assert res_a is None  # cold -> caller host-serves
        st = arena.stats()
        assert st["resident_bytes"] <= budget
    finally:
        arena.close()


def _build_lsm_volume(tmp_path, rng, vid, n=3000):
    from seaweedfs_tpu.storage.needle_map.lsm_map import LsmNeedleMap

    nm = LsmNeedleMap(
        os.path.join(str(tmp_path), f"v{vid}.idx"), memtable_bytes=1
    )
    keys = rng.choice(
        np.arange(1, 400_000, dtype=np.uint64), size=n, replace=False
    )
    for i, k in enumerate(keys.tolist()):
        nm.put(int(k), i + 1, 100 + (i % 50))
    for k in keys[:25].tolist():
        nm.delete(int(k), 0)
    return nm, keys


class _Vol:
    def __init__(self, nm):
        self.nm = nm


class _Store:
    def __init__(self):
        self.vols = {}

    def find_volume(self, vid):
        return self.vols.get(vid)


def test_volume_gate_arena_kill_degrades_to_host(tmp_path, monkeypatch):
    """The volume needle-map gate's proven host fallback: warm arena
    serves device batches; killing it mid-stream degrades every later
    wakeup to host lookups with zero identity violations."""
    from seaweedfs_tpu.server import lookup_gate as lg

    monkeypatch.setattr(lg, "_ARENA_MIN_WAKEUP", 8)
    rng = np.random.default_rng(11)
    store = _Store()
    nms = {}
    for vid in (1, 2):
        nm, keys = _build_lsm_volume(tmp_path, rng, vid)
        store.vols[vid] = _Vol(nm)
        nms[vid] = keys
    arena = DeviceColumnArena()
    gate = lg.BatchLookupGate(store, arena=arena, identity_check=True)
    try:

        async def probe_round(n):
            futs, checks = [], []
            for vid in (1, 2):
                keys = nms[vid]
                for k in rng.integers(1, 400_000, size=n).tolist():
                    futs.append(gate.lookup(vid, int(k)))
                    checks.append((vid, int(k)))
                for k in keys[30:50].tolist():
                    futs.append(gate.lookup(vid, int(k)))
                    checks.append((vid, int(k)))
            res = await asyncio.gather(*futs)
            for (vid, k), r in zip(checks, res):
                nv = store.vols[vid].nm.get(k)
                from seaweedfs_tpu.types import TOMBSTONE_FILE_SIZE

                want = (
                    (nv.offset_units, nv.size)
                    if nv is not None
                    and nv.offset_units != 0
                    and nv.size != TOMBSTONE_FILE_SIZE
                    else None
                )
                assert r == want, (vid, k, r, want)

        async def main():
            await probe_round(60)  # cold -> host fallback
            arena.refresh_sync()
            await probe_round(60)  # warm -> device
            assert gate.stats["device_batches"] > 0
            arena.kill()  # chaos: arena dies mid-serving
            await probe_round(60)  # degraded -> host, still correct
            assert gate.stats["identity_mismatches"] == 0
            assert gate.stats["host_fallbacks"] > 0

        asyncio.run(main())
    finally:
        gate.close()
        arena.close()
        for v in store.vols.values():
            v.nm.close()


def test_meta_gate_arena_kill_degrades_to_host(tmp_path):
    """The filer path-spine resolution path's proven host fallback:
    ragged spine chains answered by the arena, then by the host after a
    kill — entry-for-entry identical throughout."""
    from seaweedfs_tpu.filer.entry import Entry
    from seaweedfs_tpu.filer.lsm_store import LsmFilerStore
    from seaweedfs_tpu.filer.meta_gate import MetaLookupGate

    store = LsmFilerStore(
        str(tmp_path / "filer"), memtable_limit=40, fsync=False
    )
    arena = DeviceColumnArena()
    gate = MetaLookupGate(store, arena=arena, identity_check=True)
    paths = []
    try:
        for i in range(300):
            p = f"/b/dir{i % 9}/f-{i}"
            store.insert_entry(Entry(full_path=p))
            paths.append(p)
        for p in paths[:8]:
            store.delete_entry(p)

        async def spine_round():
            futs = [
                gate.lookup_many(
                    [p, "/b", f"/b/dir{i % 9}", f"/miss-{i}"]
                )
                for i, p in enumerate(paths[5:90])
            ]
            rs = await asyncio.gather(*futs)
            for (i, p), r in zip(enumerate(paths[5:90]), rs):
                if p in paths[:8]:
                    assert r[0] is None
                else:
                    assert r[0] is not None and r[0].full_path == p
                assert r[3] is None  # the miss slot

        async def main():
            await spine_round()  # cold -> host
            arena.refresh_sync()
            await spine_round()  # warm -> device
            assert gate.stats["device_batches"] > 0
            arena.kill()
            await spine_round()  # degraded -> host
            assert gate.stats["identity_mismatches"] == 0
            assert gate.stats["host_fallbacks"] > 0

        asyncio.run(main())
    finally:
        gate.close()
        arena.close()
        store.close()


def test_filer_tombstone_and_memtable_shadowing(tmp_path):
    """Memtable state — including tombstones — must shadow device
    answers from sealed segments: delete a sealed path, re-insert
    another, both visible correctly through the arena path."""
    from seaweedfs_tpu.filer.entry import Entry
    from seaweedfs_tpu.filer.lsm_store import LsmFilerStore
    from seaweedfs_tpu.filer.meta_gate import MetaLookupGate

    store = LsmFilerStore(
        str(tmp_path / "filer"), memtable_limit=20, fsync=False
    )
    arena = DeviceColumnArena()
    gate = MetaLookupGate(store, arena=arena, identity_check=False)
    try:
        paths = [f"/d/f-{i}" for i in range(60)]
        for p in paths:
            store.insert_entry(Entry(full_path=p))
        # all sealed now (memtable_limit 20); mutate IN the memtable
        store.delete_entry(paths[0])
        store.insert_entry(
            Entry(full_path=paths[1], extended={"v": "new"})
        )
        arena.ensure(store.arena_view(paths)[1])
        arena.refresh_sync()

        async def main():
            r = await gate.lookup_many([paths[0], paths[1], paths[2]])
            assert r[0] is None  # memtable tombstone shadows segment
            assert r[1] is not None and r[1].extended.get("v") == "new"
            assert r[2] is not None
            assert gate.stats["device_batches"] > 0

        asyncio.run(main())
    finally:
        gate.close()
        arena.close()
        store.close()
