"""Leader-only maintenance-script runner (ref: weed/server/
master_server.go:191-246 startAdminScripts)."""

import asyncio

from test_cluster import free_port_pair

from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.filer import FilerServer
from seaweedfs_tpu.server.volume import VolumeServer


def test_maintenance_scripts_run_on_leader(tmp_path):
    async def body():
        mport = free_port_pair()
        ms = MasterServer(
            port=mport,
            pulse_seconds=0.2,
            # no explicit lock/unlock: the runner auto-wraps the script
            maintenance_scripts="bucket.list\nbucket.create -name auto",
            maintenance_sleep_minutes=0.005,  # ~0.3s ticks
        )
        d = tmp_path / "vol"
        d.mkdir()
        vs = VolumeServer(
            master=ms.address,
            directories=[str(d)],
            port=free_port_pair(),
            pulse_seconds=0.2,
        )
        fs = FilerServer(master=ms.address, port=free_port_pair())
        ms.maintenance_filer = fs.address
        await ms.start()
        await vs.start()
        await fs.start()
        try:
            # the runner fires on its timer and creates the bucket
            for _ in range(100):
                if fs.filer.find_entry("/buckets/auto") is not None:
                    break
                await asyncio.sleep(0.1)
            assert fs.filer.find_entry("/buckets/auto") is not None

            # the auto-wrapped unlock released the admin lease
            for _ in range(50):
                if ms._admin_token is None:
                    break
                await asyncio.sleep(0.1)
            assert ms._admin_token is None
        finally:
            await fs.stop()
            await vs.stop()
            await ms.stop()

    asyncio.run(body())
