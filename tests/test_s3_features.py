"""S3 gateway parity: DeleteMultipleObjects, CopyObject, list pagination
(ref: weed/s3api/s3api_object_handlers.go DeleteMultipleObjectsHandler /
CopyObjectHandler, s3api_objects_list_handlers.go marker/continuation)."""

import asyncio
import random
import xml.etree.ElementTree as ET

import aiohttp

from test_cluster import Cluster, free_port_pair

from seaweedfs_tpu.s3.server import S3Server
from seaweedfs_tpu.server.filer import FilerServer


def test_s3_copy_delete_multiple_pagination(tmp_path):
    async def body():
        random.seed(83)
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        fs = FilerServer(master=cluster.master.address, port=free_port_pair())
        await fs.start()
        s3 = S3Server(fs, port=free_port_pair())
        await s3.start()
        try:
            await fs.master_client.wait_connected()
            base = f"http://{s3.address}"
            async with aiohttp.ClientSession() as session:
                async with session.put(f"{base}/pb", data=b"") as r:
                    assert r.status == 200
                payloads = {}
                for i in range(7):
                    key = f"obj-{i:02d}.bin"
                    payloads[key] = random.randbytes(500 + i)
                    async with session.put(
                        f"{base}/pb/{key}", data=payloads[key]
                    ) as r:
                        assert r.status == 200

                # --- pagination: 3 pages of 3 ---
                seen = []
                token = ""
                while True:
                    url = f"{base}/pb?list-type=2&max-keys=3"
                    if token:
                        url += f"&continuation-token={token}"
                    async with session.get(url) as r:
                        root = ET.fromstring(await r.read())
                    page = [c.findtext("Key") for c in root.findall("Contents")]
                    seen.extend(page)
                    if root.findtext("IsTruncated") == "true":
                        token = root.findtext("NextContinuationToken")
                        assert token
                    else:
                        break
                assert seen == sorted(payloads)

                # --- CopyObject ---
                async with session.put(
                    f"{base}/pb/copied.bin",
                    headers={"X-Amz-Copy-Source": "/pb/obj-03.bin"},
                ) as r:
                    assert r.status == 200, await r.text()
                    assert b"CopyObjectResult" in await r.read()
                async with session.get(f"{base}/pb/copied.bin") as r:
                    assert await r.read() == payloads["obj-03.bin"]
                # the copy owns its chunks: deleting the source keeps it
                async with session.delete(f"{base}/pb/obj-03.bin") as r:
                    assert r.status == 204
                async with session.get(f"{base}/pb/copied.bin") as r:
                    assert await r.read() == payloads["obj-03.bin"]

                # --- UploadPartCopy: multipart assembled from a source range ---
                async with session.post(
                    f"{base}/pb/assembled.bin?uploads"
                ) as r:
                    up_root = ET.fromstring(await r.read())
                    upload_id = up_root.findtext("UploadId")
                src = payloads["obj-05.bin"]
                async with session.put(
                    f"{base}/pb/assembled.bin?uploadId={upload_id}&partNumber=1",
                    headers={
                        "X-Amz-Copy-Source": "/pb/obj-05.bin",
                        "x-amz-copy-source-range": "bytes=0-99",
                    },
                ) as r:
                    assert r.status == 200, await r.text()
                    assert b"CopyPartResult" in await r.read()
                async with session.put(
                    f"{base}/pb/assembled.bin?uploadId={upload_id}&partNumber=2",
                    data=b"tail-bytes",
                ) as r:
                    assert r.status == 200
                async with session.post(
                    f"{base}/pb/assembled.bin?uploadId={upload_id}", data=b""
                ) as r:
                    assert r.status == 200
                async with session.get(f"{base}/pb/assembled.bin") as r:
                    assert await r.read() == src[:100] + b"tail-bytes"

                # --- DeleteMultipleObjects (namespaced XML, as AWS SDKs send) ---
                body_xml = (
                    '<Delete xmlns="http://s3.amazonaws.com/doc/2006-03-01/">'
                    + "".join(
                        f"<Object><Key>obj-{i:02d}.bin</Key></Object>"
                        for i in range(3)
                    )
                    + "</Delete>"
                )
                async with session.post(
                    f"{base}/pb?delete", data=body_xml
                ) as r:
                    assert r.status == 200
                    root = ET.fromstring(await r.read())
                    deleted = [
                        d.findtext("Key") for d in root.findall("Deleted")
                    ]
                    assert deleted == [f"obj-{i:02d}.bin" for i in range(3)]
                for i in range(3):
                    async with session.get(f"{base}/pb/obj-{i:02d}.bin") as r:
                        assert r.status == 404
        finally:
            await s3.stop()
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())
