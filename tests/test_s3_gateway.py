"""Object-gateway fast path (ISSUE 7): shared serving core, leased
chunked uploads, range-scan LIST, hedged chunk reads, batched chunk GC.

Covers the satellite checklist:
- multipart upload e2e: initiate -> parts -> complete -> byte-identical
  ranged GETs;
- ListObjectsV2 pagination property: seeded key sets, page concatenation
  over continuation tokens == full sorted listing, CommonPrefixes
  correct under delimiter, per-page scan work bounded;
- hedged `_fetch_chunk` with one dead volume replica;
- `_findtext_local` direct-children fix;
- batched deletion loop drains overwrite garbage without a linger window.
"""

import asyncio
import random
import xml.etree.ElementTree as ET

from test_cluster import Cluster, free_port_pair


# ---------------------------------------------------------------- units --


def test_findtext_local_direct_children_only():
    """A same-named element nested under an unrelated node (e.g. a <Key>
    inside a CompleteMultipartUpload part list) must not shadow the
    direct child the caller means."""
    from seaweedfs_tpu.s3.server import _findtext_local

    root = ET.fromstring(
        "<Delete><Object><Key>nested</Key></Object><Quiet>true</Quiet>"
        "</Delete>"
    )
    assert _findtext_local(root, "Key") == ""  # no DIRECT Key child
    assert _findtext_local(root, "Quiet") == "true"
    obj = root.find("Object")
    assert _findtext_local(obj, "Key") == "nested"
    # namespace-agnostic on direct children, as before
    ns = ET.fromstring(
        '<R xmlns="http://s3.amazonaws.com/doc/2006-03-01/"><K>v</K></R>'
    )
    assert _findtext_local(ns, "K") == "v"


def _populate(filer, keys):
    for k in keys:
        try:
            filer.touch("/buckets/b/" + k, "", [])
        except OSError:
            pass  # key collides with an existing file-as-directory


def _file_keys(filer):
    out = []

    def walk(d, rel):
        for e in filer.list_entries(d, limit=100_000):
            if e.is_directory:
                walk(e.full_path, rel + e.name + "/")
            else:
                out.append(rel + e.name)

    walk("/buckets/b", "")
    return sorted(out)


def _make_filer(store_kind, tmp_path):
    from seaweedfs_tpu.filer.filer import Filer
    from seaweedfs_tpu.filer.filer_store import (
        MemoryFilerStore,
        SqliteFilerStore,
    )
    from seaweedfs_tpu.filer.lsm_store import LsmFilerStore

    if store_kind == "memory":
        return Filer(MemoryFilerStore())
    if store_kind == "sqlite":
        tmp_path.mkdir(parents=True, exist_ok=True)
        return Filer(SqliteFilerStore(str(tmp_path / "filer.db")))
    return Filer(LsmFilerStore(str(tmp_path / "lsm"), fsync=False))


def test_list_objects_pagination_property(tmp_path):
    """Seeded key sets on BOTH store families: concatenating pages over
    continuation tokens reproduces the full sorted listing exactly, with
    and without a delimiter; CommonPrefixes match a brute-force
    reference; per-page store work stays O(page), not O(bucket)."""
    from seaweedfs_tpu.filer.filer_store import ScanStats
    from seaweedfs_tpu.s3.server import list_objects_page

    rng = random.Random(0xE707)

    def rand_key():
        return "/".join(
            "".join(rng.choice("ab0z!-.") for _ in range(rng.randint(1, 4)))
            for _ in range(rng.randint(1, 3))
        )

    for store_kind in ("memory", "lsm", "sqlite"):
        filer = _make_filer(store_kind, tmp_path / store_kind)
        _populate(filer, {rand_key() for _ in range(120)})
        expected = _file_keys(filer)
        assert len(expected) > 50

        # no delimiter: page concatenation == full sorted listing
        for max_keys in (1, 7, 1000):
            after, pages = "", []
            for _ in range(10_000):
                items, trunc = list_objects_page(
                    filer, "/buckets/b", max_keys=max_keys, after=after
                )
                pages.extend(k for k, _e in items)
                if not trunc or not items:
                    break
                after = items[-1][0]
            assert pages == expected, (store_kind, max_keys)

        # delimiter "/": CommonPrefixes vs a brute-force reference, and
        # pagination must agree with the one-shot listing
        for prefix in ("", "a", "a/"):
            one_shot, _ = list_objects_page(
                filer, "/buckets/b", prefix=prefix, max_keys=100_000,
                delimiter="/",
            )
            ref_groups, ref_contents = set(), []
            for k in expected:
                if not k.startswith(prefix):
                    continue
                i = k.find("/", len(prefix))
                if i >= 0:
                    ref_groups.add(k[: i + 1])
                else:
                    ref_contents.append(k)
            got_contents = [k for k, e in one_shot if e is not None]
            got_groups = {k for k, e in one_shot if e is None}
            assert got_contents == ref_contents, (store_kind, prefix)
            # directories always exist for every group; empty dirs may
            # add groups a file-derived reference lacks, never lose any
            assert ref_groups <= got_groups, (store_kind, prefix)
            after, paged = "", []
            for _ in range(10_000):
                items, trunc = list_objects_page(
                    filer, "/buckets/b", prefix=prefix, after=after,
                    max_keys=3, delimiter="/",
                )
                paged.extend(items)
                if not trunc or not items:
                    break
                after = items[-1][0]
            assert [(k, e is None) for k, e in paged] == [
                (k, e is None) for k, e in one_shot
            ], (store_kind, prefix)


def test_list_scan_work_is_page_bounded(tmp_path):
    """The acceptance counter assertion: a bucket >= 100x the page size,
    one page's scanned-entry count bounded by O(max-keys + groups)."""
    from seaweedfs_tpu.filer.filer_store import ScanStats
    from seaweedfs_tpu.s3.server import list_objects_page

    filer = _make_filer("lsm", tmp_path)
    n, page = 2600, 25  # 104x the page size
    for i in range(n):
        filer.touch(f"/buckets/b/d{i % 20:02d}/k{i:06d}", "", [])

    st = ScanStats()
    items, trunc = list_objects_page(
        filer, "/buckets/b", max_keys=page, stats=st
    )
    assert len(items) == page and trunc
    assert st.scanned <= 4 * (page + 20), st.scanned

    # delimiter page: 20 groups, scanned ~ groups, NOT the 2600 keys
    st2 = ScanStats()
    items2, _ = list_objects_page(
        filer, "/buckets/b", max_keys=page, delimiter="/", stats=st2
    )
    assert len(items2) == 20 and all(e is None for _k, e in items2)
    assert st2.scanned <= 4 * page, st2.scanned

    # mid-bucket resume stays bounded too
    st3 = ScanStats()
    list_objects_page(
        filer, "/buckets/b", after="d13/k001351", max_keys=page, stats=st3
    )
    assert st3.scanned <= 4 * (page + 20), st3.scanned

    # max-keys=0 (legal existence probe): empty, NOT truncated — a
    # truncated-with-no-token answer would loop token-following SDKs
    items0, trunc0 = list_objects_page(filer, "/buckets/b", max_keys=0)
    assert items0 == [] and trunc0 is False


# ------------------------------------------------------------- cluster --


def test_multipart_e2e_ranged_gets(tmp_path):
    """initiate -> 3 parts -> complete (metadata-only merge) -> whole and
    RANGED GETs byte-identical to the assembled parts, through the fast
    tier (plain GET) and the range path (visible intervals fetched
    concurrently)."""

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=2)
        await cluster.start()
        from seaweedfs_tpu.s3.server import S3Server
        from seaweedfs_tpu.server.filer import FilerServer
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        fs = FilerServer(
            master=cluster.master.address,
            port=free_port_pair(),
            chunk_size=64 * 1024,  # parts span multiple chunks
        )
        await fs.start()
        s3 = S3Server(fs, port=free_port_pair())
        await s3.start()
        http = FastHTTPClient()
        try:
            await fs.master_client.wait_connected()
            st, _ = await http.request("PUT", s3.address, "/mb")
            assert st == 200
            st, resp = await http.request(
                "POST", s3.address, "/mb/obj.bin?uploads"
            )
            upload_id = ET.fromstring(resp).findtext("UploadId")
            parts = [random.randbytes(80 * 1024 + i) for i in range(3)]
            for i, part in enumerate(parts, start=1):
                st, resp = await http.request(
                    "PUT",
                    s3.address,
                    f"/mb/obj.bin?uploadId={upload_id}&partNumber={i}",
                    body=part,
                )
                assert st == 200, (st, resp)
            st, resp = await http.request(
                "POST", s3.address, f"/mb/obj.bin?uploadId={upload_id}"
            )
            assert st == 200, (st, resp)
            etag = ET.fromstring(resp).findtext("ETag")
            assert etag.strip('"').endswith("-3")

            whole = b"".join(parts)
            st, got = await http.request("GET", s3.address, "/mb/obj.bin")
            assert st == 200 and got == whole

            size = len(whole)
            spans = [
                (0, 1000),
                (79_000, 82_000),          # crosses part 1 -> 2
                (160_000, size - 1),       # crosses part 2 -> 3 to EOF
                (size - 500, size - 1),
            ]
            for lo, hi in spans:
                st, got = await http.request(
                    "GET", s3.address, "/mb/obj.bin",
                    headers={"Range": f"bytes={lo}-{hi}"},
                )
                assert st == 206, (lo, hi, st)
                assert got == whole[lo : hi + 1], (lo, hi)
            st, _got = await http.request(
                "GET", s3.address, "/mb/obj.bin",
                headers={"Range": f"bytes={size + 10}-{size + 20}"},
            )
            assert st == 416

            # HTTP-level ListObjectsV2 pagination over the gateway
            for i in range(7):
                st, _ = await http.request(
                    "PUT", s3.address, f"/mb/p/{i}.x", body=b"x"
                )
                assert st == 200
            token, keys = "", []
            for _ in range(50):
                target = "/mb?list-type=2&max-keys=3"
                if token:
                    target += f"&continuation-token={token}"
                st, resp = await http.request("GET", s3.address, target)
                assert st == 200
                tree = ET.fromstring(resp)
                keys += [c.findtext("Key") for c in tree.findall("Contents")]
                if tree.findtext("IsTruncated") != "true":
                    break
                token = tree.findtext("NextContinuationToken")
            assert keys == sorted(["obj.bin"] + [f"p/{i}.x" for i in range(7)])
        finally:
            await http.close()
            await s3.stop()
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())


def test_fetch_chunk_hedged_failover_dead_replica(tmp_path):
    """With replication 001 and one volume server stopped, filer reads
    still succeed through the replica fan-out's dead-replica failover
    (`client/read_fanout` behind `_fetch_chunk`)."""

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=2)
        await cluster.start()
        from seaweedfs_tpu.server.filer import FilerServer
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        fs = FilerServer(
            master=cluster.master.address,
            port=free_port_pair(),
            replication="001",
        )
        await fs.start()
        http = FastHTTPClient()
        try:
            await fs.master_client.wait_connected()
            payload = random.randbytes(9000)
            st, resp = await http.request(
                "PUT", fs.address, "/r/file.bin", body=payload,
                content_type="application/octet-stream",
            )
            assert st == 201, (st, resp)
            entry = fs.filer.find_entry("/r/file.bin")
            vid = int(entry.chunks[0].fid.split(",")[0])
            # both replicas known to the filer's vid map
            for _ in range(100):
                if len(fs.master_client.vid_map.lookup(vid)) == 2:
                    break
                await asyncio.sleep(0.1)
            assert len(fs.master_client.vid_map.lookup(vid)) == 2

            # kill one replica's HTTP serving only (heartbeats keep
            # advertising it, like a wedged-but-not-deregistered server,
            # so the vid map KEEPS the dead location and the failover
            # path — not master deregistration — must save the reads)
            locs = fs.master_client.vid_map.lookup(vid)
            victim = next(
                vs for vs in cluster.volume_servers if vs.address in locs
            )
            await victim._core.stop()

            # every read must succeed: whichever rotation starts at the
            # dead holder fails over to the live peer
            for _ in range(8):
                st, got = await http.request("GET", fs.address, "/r/file.bin")
                assert st == 200
                assert got == payload
            assert fs._chunk_reader.hedges > 0  # failover actually fired
        finally:
            await http.close()
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())


def test_overwrite_drains_chunk_deletion_batch(tmp_path):
    """PUT-over-existing queues the replaced chunks; the batched
    deletion loop drains them promptly via per-host BatchDelete (no
    fixed-interval linger window) and the old needle 404s."""

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        from seaweedfs_tpu.server.filer import FilerServer
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        fs = FilerServer(master=cluster.master.address, port=free_port_pair())
        await fs.start()
        http = FastHTTPClient()
        try:
            await fs.master_client.wait_connected()
            st, _ = await http.request(
                "PUT", fs.address, "/gc/a.bin", body=b"v1" * 400,
                content_type="application/octet-stream",
            )
            assert st == 201
            old = fs.filer.find_entry("/gc/a.bin").chunks[0]
            vs = cluster.volume_servers[0]
            st, _ = await http.request("GET", vs.address, "/" + old.fid)
            assert st == 200
            st, _ = await http.request(
                "PUT", fs.address, "/gc/a.bin", body=b"v2" * 400,
                content_type="application/octet-stream",
            )
            assert st == 201
            for _ in range(100):
                st, _ = await http.request("GET", vs.address, "/" + old.fid)
                if st == 404:
                    break
                await asyncio.sleep(0.05)
            assert st == 404, "old chunk still readable: deletion leaked"
            assert fs.chunk_delete_rounds >= 1
        finally:
            await http.close()
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())


def test_object_cache_validates_against_live_entry(tmp_path):
    """The gateway object-response cache serves hits byte-identical and
    NEVER serves stale bytes across overwrite/delete — the signature
    check against the live entry is the invalidation."""

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        from seaweedfs_tpu.s3.server import S3Server
        from seaweedfs_tpu.server.filer import FilerServer
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        fs = FilerServer(master=cluster.master.address, port=free_port_pair())
        await fs.start()
        s3 = S3Server(fs, port=free_port_pair())
        await s3.start()
        http = FastHTTPClient()
        try:
            await fs.master_client.wait_connected()
            assert s3.object_cache is not None
            await http.request("PUT", s3.address, "/cb")
            v1 = random.randbytes(4000)
            st, _ = await http.request("PUT", s3.address, "/cb/k", body=v1)
            assert st == 200
            st, a = await http.request("GET", s3.address, "/cb/k")  # fill
            st2, b = await http.request("GET", s3.address, "/cb/k")  # hit
            assert st == st2 == 200 and a == b == v1
            assert s3.object_cache.hits >= 1

            v2 = random.randbytes(5000)
            st, _ = await http.request("PUT", s3.address, "/cb/k", body=v2)
            assert st == 200
            st, c = await http.request("GET", s3.address, "/cb/k")
            assert st == 200 and c == v2  # signature changed: no stale hit

            st, _ = await http.request("DELETE", s3.address, "/cb/k")
            assert st == 204
            st, _ = await http.request("GET", s3.address, "/cb/k")
            assert st == 404
        finally:
            await http.close()
            await s3.stop()
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())


def test_fault_seam_fires_on_gateway_requests(tmp_path):
    """The server-side HTTP seam in the shared serving core: existing
    fault-plan shapes (latency/http_error) fire on S3 gateway requests —
    op http:<METHOD>, target = the gateway's own listen address."""

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        from seaweedfs_tpu.s3.server import S3Server
        from seaweedfs_tpu.server.filer import FilerServer
        from seaweedfs_tpu.util import faults
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        fs = FilerServer(master=cluster.master.address, port=free_port_pair())
        await fs.start()
        s3 = S3Server(fs, port=free_port_pair())
        await s3.start()
        http = FastHTTPClient()
        try:
            await fs.master_client.wait_connected()
            await http.request("PUT", s3.address, "/fb")
            st, _ = await http.request("PUT", s3.address, "/fb/k", body=b"x")
            assert st == 200
            plan = faults.FaultPlan(
                seed=1,
                rules=[
                    faults.FaultRule(
                        op="http:GET", target=f"*:{s3.port}", nth=1,
                        fault="http_error", status=503,
                    ),
                    faults.FaultRule(
                        op="http:GET", target=f"*:{s3.port}",
                        probability=1.0, fault="latency", delay=0.05,
                    ),
                ],
            )
            faults.install_plan(plan)
            try:
                import time as _time

                st, _ = await http.request("GET", s3.address, "/fb/k")
                assert st == 503  # injected, never reached the handler
                t0 = _time.perf_counter()
                st, got = await http.request("GET", s3.address, "/fb/k")
                dt = _time.perf_counter() - t0
                assert st == 200 and got == b"x"
                assert dt >= 0.04  # the latency rule delayed the request
                assert plan.fired("http:GET") >= 2
            finally:
                faults.clear_plan()
        finally:
            await http.close()
            await s3.stop()
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())


def test_chunk_upload_gate_batches_concurrent_puts(tmp_path):
    """Concurrent _write_chunks calls coalesce into /!batch/put rounds
    (largest_batch > 1) and every chunk reads back byte-identical from
    the volume tier."""

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        from seaweedfs_tpu.server.filer import FilerServer
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        fs = FilerServer(master=cluster.master.address, port=free_port_pair())
        await fs.start()
        http = FastHTTPClient()
        try:
            await fs.master_client.wait_connected()
            assert fs._upload_gate is not None
            payloads = [
                bytes([i]) * (1000 + i) for i in range(16)
            ]
            chunk_lists = await asyncio.gather(
                *(fs._write_chunks(p) for p in payloads)
            )
            assert fs._upload_gate.stats["largest_batch"] > 1
            vs = cluster.volume_servers[0]
            for p, chunks in zip(payloads, chunk_lists):
                assert len(chunks) == 1
                st, got = await http.request(
                    "GET", vs.address, "/" + chunks[0].fid
                )
                assert st == 200 and got == p
        finally:
            await http.close()
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())


# ------- PR 7 follow-up satellites: sqlite scan pushdown + UploadPartCopy -----


def test_sqlite_list_scan_pushes_prefix_bound_into_query(tmp_path):
    """A prefix-bounded LIST page over the sqlite store must pull only
    rows inside the prefix range (the upper bound rides the indexed SQL
    predicate), not a generic page it then discards — scanned-rows-per-
    page matches the in-memory stores' O(max-keys) bound."""
    from seaweedfs_tpu.filer.filer_store import ScanStats, scan_subtree

    filer = _make_filer("sqlite", tmp_path / "sq")
    # one flat directory: 400 keys below the prefix, 3 inside it
    _populate(filer, {f"a{i:04d}" for i in range(400)} | {"zz1", "zz2", "zz3"})

    stats = ScanStats()
    got = [k for k, _e in scan_subtree(
        filer.store, "/buckets/b", prefix="zz", stats=stats
    )]
    assert got == ["zz1", "zz2", "zz3"]
    # the indexed range predicate pulls exactly the in-range rows: the
    # 400 "a*" rows below the floor are never enumerated, and the final
    # page is not padded with out-of-range rows
    assert stats.scanned == 3, stats.scanned

    # same shape on the memory store for comparison: the generic page
    # path also stays bounded (floor seek), so both satisfy the O(page)
    # claim — sqlite just stops AT the range end exactly
    filer_mem = _make_filer("memory", tmp_path / "mem")
    _populate(
        filer_mem, {f"a{i:04d}" for i in range(400)} | {"zz1", "zz2", "zz3"}
    )
    stats_mem = ScanStats()
    got_mem = [k for k, _e in scan_subtree(
        filer_mem.store, "/buckets/b", prefix="zz", stats=stats_mem
    )]
    assert got_mem == got
    assert stats_mem.scanned <= 64  # one page at most


def test_filer_shared_fid_ledger_frees_on_last_release(tmp_path):
    """add_fid_refs / release_fids: a fid listed by two entries is freed
    only when the LAST referencing entry releases it, in either deletion
    order, and the ledger survives a filer restart."""
    from seaweedfs_tpu.filer.filer import Filer
    from seaweedfs_tpu.filer.filer_store import SqliteFilerStore

    for order in ("source_first", "copy_first"):
        freed = []
        db = str(tmp_path / f"refs_{order}.db")
        filer = Filer(SqliteFilerStore(db), on_delete_chunks=freed.extend)
        from seaweedfs_tpu.filer import FileChunk

        chunk = FileChunk(fid="9,aa00bb", offset=0, size=10)
        filer.touch("/buckets/b/src", "", [chunk])
        filer.add_fid_refs([chunk.fid])
        filer.touch("/buckets/b/copy", "", [chunk])

        # restart: the ledger must come back from the durable store
        filer2 = Filer(SqliteFilerStore(db), on_delete_chunks=freed.extend)
        first, second = (
            ("/buckets/b/src", "/buckets/b/copy")
            if order == "source_first"
            else ("/buckets/b/copy", "/buckets/b/src")
        )
        filer2.delete_entry(first)
        assert freed == [], (order, freed)  # extra ref burned, not freed
        filer2.delete_entry(second)
        assert freed == [chunk.fid], (order, freed)  # last ref frees


def test_upload_part_copy_references_aligned_chunks(tmp_path):
    """UploadPartCopy over a chunk-aligned range references the source
    fids (no byte re-upload); unaligned edges fall back to the byte
    path; the assembled object stays byte-identical after the SOURCE is
    deleted (the shared-fid ledger protects borrowed chunks)."""
    import xml.etree.ElementTree as ET

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=2)
        await cluster.start()
        from seaweedfs_tpu.s3.server import S3Server
        from seaweedfs_tpu.server.filer import FilerServer
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        CH = 64 * 1024
        fs = FilerServer(
            master=cluster.master.address, port=free_port_pair(),
            chunk_size=CH,
        )
        await fs.start()
        s3 = S3Server(fs, port=free_port_pair())
        await s3.start()
        http = FastHTTPClient()
        try:
            await fs.master_client.wait_connected()
            st, _ = await http.request("PUT", s3.address, "/cb")
            assert st == 200
            src = random.randbytes(4 * CH)  # exactly 4 aligned chunks
            st, _ = await http.request(
                "PUT", s3.address, "/cb/src.bin", body=src
            )
            assert st == 200
            src_entry = s3.filer.find_entry("/buckets/cb/src.bin")
            src_fids = [c.fid for c in sorted(
                src_entry.chunks, key=lambda c: c.offset
            )]
            assert len(src_fids) == 4

            st, resp = await http.request(
                "POST", s3.address, "/cb/asm.bin?uploads"
            )
            upload_id = ET.fromstring(resp).findtext("UploadId")

            # part 1: chunks 2..3 exactly (aligned) -> pure references.
            # Issued TWICE (a client retry after a lost response): the
            # overwrite must burn the duplicate refs, or the needles
            # leak forever (ledger-empty assertion at the end)
            for _attempt in range(2):
                st, resp = await http.request(
                    "PUT", s3.address,
                    f"/cb/asm.bin?uploadId={upload_id}&partNumber=1",
                    headers={
                        "x-amz-copy-source": "/cb/src.bin",
                        "x-amz-copy-source-range": (
                            f"bytes={CH}-{3 * CH - 1}"
                        ),
                    },
                )
                assert st == 200, resp
            part1 = s3.filer.find_entry(
                f"/buckets/.uploads/{upload_id}/00001.part"
            )
            part1_fids = [c.fid for c in part1.chunks]
            assert part1_fids == src_fids[1:3]  # referenced, not copied

            # part 2: unaligned head (mid-chunk) + aligned chunk 4 ->
            # one fresh edge chunk + one reference
            st, resp = await http.request(
                "PUT", s3.address,
                f"/cb/asm.bin?uploadId={upload_id}&partNumber=2",
                headers={
                    "x-amz-copy-source": "/cb/src.bin",
                    "x-amz-copy-source-range": (
                        f"bytes={3 * CH - 100}-{4 * CH - 1}"
                    ),
                },
            )
            assert st == 200, resp
            part2 = s3.filer.find_entry(
                f"/buckets/.uploads/{upload_id}/00002.part"
            )
            p2_fids = {c.fid for c in part2.chunks}
            assert src_fids[3] in p2_fids  # whole chunk 4 referenced
            assert len(p2_fids - set(src_fids)) == 1  # the edge re-upload

            st, resp = await http.request(
                "POST", s3.address, f"/cb/asm.bin?uploadId={upload_id}"
            )
            assert st == 200, resp
            expect = src[CH : 3 * CH] + src[3 * CH - 100 :]
            st, got = await http.request("GET", s3.address, "/cb/asm.bin")
            assert st == 200 and got == expect

            # delete the SOURCE: borrowed fids survive via the ledger
            st, _ = await http.request("DELETE", s3.address, "/cb/src.bin")
            assert st == 204
            await asyncio.sleep(0.5)  # let the deletion loop drain
            st, got = await http.request("GET", s3.address, "/cb/asm.bin")
            assert st == 200 and got == expect, "borrowed chunks were freed"

            # delete the copy too: every extra ref burns down and the
            # ledger ends empty (nothing leaks)
            st, _ = await http.request("DELETE", s3.address, "/cb/asm.bin")
            assert st == 204
            assert s3.filer._fid_refs() == {}
        finally:
            await http.close()
            await s3.stop()
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())
