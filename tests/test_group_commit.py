"""Group-commit write worker: batched fsync + truncate rollback."""

import asyncio
import random

import aiohttp
import pytest

from seaweedfs_tpu.storage.group_commit import GroupCommitWorker
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume


def test_group_commit_concurrent_writes(tmp_path):
    async def body():
        v = Volume(str(tmp_path), "", 1)
        worker = GroupCommitWorker(v)
        worker.start()
        try:
            payloads = {i: random.randbytes(500) for i in range(1, 60)}

            async def one(nid):
                n = Needle(cookie=9, id=nid, data=payloads[nid])
                offset, size, unchanged = await worker.write(n)
                assert not unchanged

            await asyncio.gather(*(one(nid) for nid in payloads))
            for nid, data in payloads.items():
                got = Needle(id=nid)
                v.read_needle(got)
                assert got.data == data
            # delete through the worker too
            freed = await worker.delete(Needle(id=1, cookie=9))
            assert freed > 0
        finally:
            await worker.stop()
            v.close()

    asyncio.run(body())


def test_group_commit_adaptive_batching(tmp_path):
    """The adaptive window must amortize concurrent writers into shared
    fsync batches (far fewer batches than requests) while a lone writer
    still flushes immediately — and the stats must record both."""

    async def body():
        v = Volume(str(tmp_path), "", 3)
        worker = GroupCommitWorker(v)
        worker.start()
        try:
            # lone writer: one request = one batch, flushed immediately
            await worker.write(Needle(cookie=1, id=1, data=b"solo"))
            assert worker.stats["batches"] == 1
            assert worker.stats["requests"] == 1

            # sustained concurrency: batches must coalesce
            async def one(nid):
                await worker.write(
                    Needle(cookie=1, id=nid, data=b"x" * 400)
                )

            n = 160
            await asyncio.gather(*(one(i) for i in range(2, 2 + n)))
            reqs = worker.stats["requests"]
            batches = worker.stats["batches"]
            assert reqs == n + 1
            assert batches < n / 2, (
                f"adaptive coalescing failed: {batches} fsyncs for "
                f"{reqs} writes"
            )
            assert worker.stats["largest_batch"] > 1
        finally:
            await worker.stop()
            v.close()

    asyncio.run(body())


def test_group_commit_rollback_on_sync_failure(tmp_path):
    async def body():
        v = Volume(str(tmp_path), "", 2)
        v.write_needle(Needle(cookie=1, id=100, data=b"pre-existing"))
        good_end = v.data_backend.size()

        real_sync = v.data_backend.sync
        v.data_backend.sync = lambda: (_ for _ in ()).throw(OSError("disk gone"))
        worker = GroupCommitWorker(v)
        worker.start()
        try:
            with pytest.raises(OSError):
                await worker.write(Needle(cookie=1, id=101, data=b"doomed"))
            # the batch was rolled back: file truncated to the pre-batch end
            assert v.data_backend.size() == good_end
        finally:
            await worker.stop()
            v.data_backend.sync = real_sync
            v.close()

    asyncio.run(body())


def test_fsync_http_path(tmp_path):
    from test_cluster import Cluster

    from seaweedfs_tpu.client import assign
    from seaweedfs_tpu.client.operation import read_url

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        try:
            async with aiohttp.ClientSession() as session:
                ar = await assign(cluster.master.address)
                form = aiohttp.FormData()
                form.add_field("file", b"fsync-payload", filename="f")
                async with session.post(
                    f"http://{ar.url}/{ar.fid}?fsync=true", data=form
                ) as resp:
                    assert resp.status == 201, await resp.text()
                got = await read_url(session, f"http://{ar.url}/{ar.fid}")
                assert got == b"fsync-payload"
        finally:
            await cluster.stop()

    asyncio.run(body())
