"""Subprocess cluster fixture lifecycle edges (ISSUE 16).

The chaos soak's value rests on the fixture's guarantees, so each one
gets a direct test: readiness failure modes raise StartupError (with
the child's log tail) AND leave no orphaned processes; teardown on an
exception inside the `with` body reaps every child; a fault plan keyed
by role reaches exactly the children of that role through the
SEAWEEDFS_TPU_FAULTS env seam (asserted by scraping faults_injected
out of the CHILD's /metrics — the only window into another process);
and SIGKILL + respawn comes back as a NEW pid serving the same port.
"""

import os
import time
import urllib.request

import pytest

from seaweedfs_tpu.ops.proc_cluster import (
    ProcCluster,
    StartupError,
    sum_metric,
)
from seaweedfs_tpu.util.faults import FaultPlan, FaultRule


def _gone(pid: int, wait_s: float = 5.0) -> bool:
    """True once `pid` no longer exists (zombies already reaped by the
    fixture's wait())."""
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return True
        time.sleep(0.05)
    return False


def _collect_pids(cluster: ProcCluster) -> list:
    return [
        c.proc.pid for c in cluster.children.values() if c.proc is not None
    ]


def test_readiness_timeout_raises_and_reaps(tmp_path):
    # a deadline no python process can meet: the probe must time out,
    # name the child, include its log tail, and reap what was spawned
    cluster = ProcCluster(str(tmp_path), volumes=0, ready_timeout=0.05)
    with pytest.raises(StartupError) as ei:
        cluster.start()
    assert "not ready" in str(ei.value)
    for pid in _collect_pids(cluster):
        assert _gone(pid), f"orphaned child pid {pid} after timeout"


def test_child_death_during_startup_raises_and_reaps(tmp_path):
    # a non-numeric pulse makes every child die in arg parsing
    # (float() raises) — the probe must report the EXIT, not wait out
    # the full readiness deadline
    cluster = ProcCluster(
        str(tmp_path), volumes=0, pulse_seconds="bogus", ready_timeout=30.0
    )
    t0 = time.monotonic()
    with pytest.raises(StartupError) as ei:
        cluster.start()
    assert "exited" in str(ei.value)
    assert time.monotonic() - t0 < 20.0, "waited out deadline on a corpse"
    for pid in _collect_pids(cluster):
        assert _gone(pid), f"orphaned child pid {pid} after startup death"


def test_teardown_on_exception_leaves_no_orphans(tmp_path):
    pids = []
    with pytest.raises(RuntimeError):
        with ProcCluster(str(tmp_path), volumes=1) as cluster:
            pids = _collect_pids(cluster)
            assert len(pids) >= 2  # master + volume at minimum
            raise RuntimeError("body blew up mid-test")
    assert pids, "cluster never started"
    for pid in pids:
        assert _gone(pid), f"orphaned child pid {pid} after exception"


def test_fault_plan_env_reaches_role_children_only(tmp_path):
    # plan keyed by ROLE: the volume child must load it from
    # SEAWEEDFS_TPU_FAULTS at import and fire it; the master (no plan)
    # must fire nothing — proven via each child's own /metrics
    plan = FaultPlan(
        seed=0xBEEF,
        rules=[
            FaultRule(
                op="http:GET", target="*", nth=1,
                fault="latency", delay=0.005,
            )
        ],
    )
    with ProcCluster(
        str(tmp_path), volumes=1, fault_plans={"volume": plan}
    ) as cluster:
        addr = cluster.address("volume-0")
        # any GET at the volume trips the nth=1 latency rule
        with urllib.request.urlopen(
            f"http://{addr}/status", timeout=5
        ) as r:
            assert r.status == 200
        fired = sum_metric(
            cluster.scrape_metrics("volume-0"),
            "seaweedfs_tpu_faults_injected_total",
        )
        assert fired >= 1, "seeded fault plan never fired in the child"
        master_fired = sum_metric(
            cluster.scrape_metrics("master"),
            "seaweedfs_tpu_faults_injected_total",
        )
        assert master_fired == 0, "plan leaked into a role without one"


def test_restart_recovers_with_new_pid(tmp_path):
    with ProcCluster(str(tmp_path), volumes=1) as cluster:
        before = cluster.children["volume-0"].pid
        served_before = cluster.served_pid("volume-0")
        assert served_before == before
        after = cluster.restart("volume-0")
        assert after != before
        assert cluster.served_pid("volume-0") == after
