"""ISSUE 13: out-of-core LSM needle map + instant mount + batch append.

Map layer: seeded oracle property through interleaved put/delete/
overwrite with forced flushes/merges, crash shapes (torn snapshot, torn
run, torn idx tail, no-close restart), and the manifest binding that
rejects a wholesale .idx rewrite. The reference semantic for every
reopen is a fresh dict replay of the same log (load_needle_map) — the
pre-ISSUE mount path IS the oracle.

Volume layer: vacuum-commit-swap and tail-sync against the lsm kind,
including the crash window where a stale snapshot survives the commit's
renames; the coalesced write_needle_batch; the group-commit frame path.

Server layer: the tenant-tagged `!batch/put` frame end-to-end and the
gRPC byte-quota seam.
"""

import asyncio
import os
import random
import shutil

import numpy as np
import pytest

from seaweedfs_tpu.types import TOMBSTONE_FILE_SIZE
from seaweedfs_tpu.storage.needle_map import (
    LsmNeedleMap,
    load_lsm_needle_map,
    load_needle_map,
    new_lsm_needle_map,
)
from seaweedfs_tpu.storage.needle_map.disk_maps import (
    metric_from_index_file,
)
from seaweedfs_tpu.storage.needle_map.lsm_map import (
    MANIFEST_EXT,
    fold_live_columns,
    invalidate_snapshot,
    sweep_snapshot_files,
)


def _small_map(idx_path, memtable=48, runs=3) -> LsmNeedleMap:
    m = new_lsm_needle_map(str(idx_path))
    m.memtable_limit = memtable  # force frequent flushes/merges
    m.max_runs = runs
    return m


def _drive(m, rng, ops, keyspace=300):
    """Interleaved put/overwrite/delete stream; returns the live oracle."""
    oracle = {}
    for _ in range(ops):
        key = rng.randrange(1, keyspace)
        if rng.random() < 0.72:
            off, size = rng.randrange(1, 1 << 20), rng.randrange(1, 4096)
            m.put(key, off, size)
            oracle[key] = (off, size)
        else:
            m.delete(key, rng.randrange(1, 1 << 20))
            oracle.pop(key, None)
    return oracle


def _assert_matches_oracle(m, oracle, keyspace=300, tag=""):
    for key in range(1, keyspace):
        nv = m.get(key)
        live = (
            nv is not None
            and nv.offset_units != 0
            and nv.size != TOMBSTONE_FILE_SIZE
        )
        if key in oracle:
            assert live, (tag, key, nv)
            assert (nv.offset_units, nv.size) == oracle[key], (tag, key)
        else:
            assert not live, (tag, key, nv)
    keys, offs, sizes = m.snapshot()
    assert keys.tolist() == sorted(oracle), tag
    for k, o, s in zip(keys.tolist(), offs.tolist(), sizes.tolist()):
        assert oracle[k] == (o, s), (tag, k)


def _assert_matches_dict_replay(idx_path, m, keyspace=300, tag=""):
    """The dict mapper's replay of the SAME log is the semantic oracle
    (what `memory`-kind mount would serve)."""
    ref = load_needle_map(str(idx_path))
    try:
        for key in range(1, keyspace):
            a, b = ref.get(key), m.get(key)
            at = (
                None
                if a is None
                or a.offset_units == 0
                or a.size == TOMBSTONE_FILE_SIZE
                else (a.offset_units, a.size)
            )
            bt = (
                None
                if b is None
                or b.offset_units == 0
                or b.size == TOMBSTONE_FILE_SIZE
                else (b.offset_units, b.size)
            )
            assert at == bt, (tag, key, at, bt)
        assert (
            ref.file_count,
            ref.deleted_count,
            ref.content_size,
            ref.deleted_size,
            ref.max_file_key,
        ) == (
            m.file_count,
            m.deleted_count,
            m.content_size,
            m.deleted_size,
            m.max_file_key,
        ), tag
    finally:
        ref.close()


@pytest.mark.parametrize("seed", [1, 7, 23])
def test_lsm_oracle_property_with_reopens(tmp_path, seed):
    """Interleaved mutations with tiny memtable/run bounds; every reopen
    flavor (clean close -> snapshot, crash -> tail replay) must match
    the dict-replay oracle of the same log, metrics included."""
    idx = tmp_path / "1.idx"
    rng = random.Random(seed)
    m = _small_map(idx)
    oracle = _drive(m, rng, 1500)
    _assert_matches_oracle(m, oracle, tag="live")
    _assert_matches_dict_replay(idx, m, tag="live-vs-dict")

    # clean close: reopen loads the snapshot, replays nothing
    m.close()
    m2 = load_lsm_needle_map(str(idx))
    assert m2.loaded_from_snapshot and m2.tail_entries_replayed == 0
    _assert_matches_oracle(m2, oracle, tag="snapshot-reopen")
    _assert_matches_dict_replay(idx, m2, tag="snapshot-vs-dict")

    # keep writing, then CRASH (no close): the reopen replays the tail
    m2.memtable_limit = 10_000  # keep the tail in the memtable
    oracle2 = dict(oracle)
    for key in range(500, 560):
        m2.put(key, key * 8, 64)
        oracle2[key] = (key * 8, 64)
    m2._idx.close()  # abrupt: no snapshot save
    m3 = load_lsm_needle_map(str(idx))
    assert m3.loaded_from_snapshot
    assert m3.tail_entries_replayed == 60
    _assert_matches_oracle(m3, oracle2, keyspace=600, tag="tail-reopen")
    _assert_matches_dict_replay(idx, m3, keyspace=600, tag="tail-vs-dict")
    m3.close()


def test_lsm_metric_equivalence(tmp_path):
    """The vectorized metric fold equals the per-entry replay metric on
    a churny log (incl. zero-size puts and repeat deletes)."""
    idx = tmp_path / "1.idx"
    m = _small_map(idx)
    rng = random.Random(5)
    for _ in range(800):
        key = rng.randrange(1, 120)
        r = rng.random()
        if r < 0.6:
            m.put(key, rng.randrange(1, 1 << 18), rng.randrange(0, 2048))
        else:
            m.delete(key, rng.randrange(1, 1 << 18))
    m.close()
    ref = metric_from_index_file(str(idx))
    got = load_lsm_needle_map(str(idx))
    assert got.loaded_from_snapshot
    assert (
        ref.file_count, ref.deletion_count, ref.file_byte_count,
        ref.deletion_byte_count, ref.maximum_file_key,
    ) == (
        got.file_count, got.deleted_count, got.content_size,
        got.deleted_size, got.max_file_key,
    )
    got.close()


@pytest.mark.parametrize("tear", ["manifest", "run", "idx"])
def test_lsm_crash_torn_artifacts(tmp_path, tear):
    """Torn snapshot artifacts (garbage manifest, truncated run file)
    degrade to a correct full rebuild; a torn idx tail (crash mid
    append) floors to the last complete entry — all three match the
    dict replay of whatever log survived."""
    idx = tmp_path / "1.idx"
    m = _small_map(idx)
    rng = random.Random(11)
    _drive(m, rng, 900)
    m.close()
    base = str(idx)[: -len(".idx")]
    if tear == "manifest":
        with open(base + MANIFEST_EXT, "r+b") as f:
            f.write(b"\x00garbage\xff")
    elif tear == "run":
        runs = [
            fn for fn in os.listdir(tmp_path) if ".nmr-" in fn
        ]
        assert runs
        victim = os.path.join(tmp_path, sorted(runs)[0])
        os.truncate(victim, os.path.getsize(victim) // 2)
    else:
        os.truncate(idx, os.path.getsize(idx) - 9)
    m2 = load_lsm_needle_map(str(idx))
    if tear in ("manifest", "run"):
        assert not m2.loaded_from_snapshot  # rejected, rebuilt
    _assert_matches_dict_replay(idx, m2, tag=f"torn-{tear}")
    m2.close()


def test_lsm_manifest_binding_rejects_rewritten_idx(tmp_path):
    """A wholesale .idx rewrite that dodges explicit invalidation (the
    crash window between a vacuum commit's renames and its
    invalidate_snapshot) must be caught by the last-entry binding: the
    stale snapshot folds the OLD log and may not be consulted."""
    from seaweedfs_tpu.storage.idx import entries_to_bytes, parse_index_bytes

    idx = tmp_path / "1.idx"
    m = _small_map(idx)
    rng = random.Random(3)
    _drive(m, rng, 600)
    m.close()
    base = str(idx)[: -len(".idx")]
    # stash the snapshot files (simulated crash keeps them around)
    stash = tmp_path / "stash"
    stash.mkdir()
    side = [
        fn
        for fn in os.listdir(tmp_path)
        if ".nmr-" in fn or fn.endswith(MANIFEST_EXT)
    ]
    for fn in side:
        shutil.copy2(tmp_path / fn, stash / fn)
    # rewrite the idx wholesale: the live set, key-sorted (what vacuum
    # and `weed fix` produce), then PADDED with fresh entries so the new
    # log is at least as long as the manifest's covered prefix — the
    # size check alone cannot reject it
    with open(idx, "rb") as f:
        keys, offs, sizes = parse_index_bytes(f.read())
    lk, lo, ls = fold_live_columns(keys, offs, sizes)
    extra = max(0, len(keys) - len(lk)) + 2
    pad_k = np.arange(10_000, 10_000 + extra, dtype=np.uint64)
    with open(idx, "wb") as f:
        f.write(entries_to_bytes(lk, lo, ls))
        f.write(
            entries_to_bytes(
                pad_k,
                np.full(extra, 7, dtype=np.uint64),
                np.full(extra, 55, dtype=np.uint32),
            )
        )
    for fn in side:
        shutil.copy2(stash / fn, tmp_path / fn)
    m2 = load_lsm_needle_map(str(idx))
    assert not m2.loaded_from_snapshot, "stale snapshot was consulted"
    _assert_matches_dict_replay(idx, m2, keyspace=10_100, tag="binding")
    m2.close()


def test_lsm_sealed_snapshot_zero_copy_and_tombstone_discipline(tmp_path):
    """A sealed map (single live run, empty memtable) serves snapshot()
    straight off the mmap'd run columns; tombstones shadow older runs
    until a rank-0 merge drops them."""
    idx = tmp_path / "1.idx"
    m = _small_map(idx, memtable=10, runs=2)
    for key in range(1, 41):
        m.put(key, key * 2, 100)
    m.delete(7, 999)
    # force everything into runs and merge down to rank 0
    m._flush_memtable()
    while len(m._runs) > 1:
        m._merge_smallest_adjacent()
    m._persist_manifest()
    assert len(m._runs) == 1 and m._runs[0].tombs == 0
    assert m.get(7) is None  # tombstone dropped at rank 0 == absent
    keys, offs, sizes = m.snapshot()
    assert 7 not in keys.tolist()
    # zero-copy: the snapshot IS the run's memmap-backed columns
    assert isinstance(keys, np.memmap) or isinstance(
        getattr(keys, "base", None), np.memmap
    )
    m.close()


def test_sealed_run_columns_reach_device_upload_uncopied(tmp_path):
    """ISSUE 14 satellite (PR 13 follow-up): the IndexSnapshot host-side
    preparation consumes a sealed map's mmap'd run columns WITHOUT
    copying the dtype-matching ones — offsets/sizes pass through as
    views of the on-disk pages, so the device upload is one DMA from
    page cache instead of transiting a heap `.astype()` copy (only the
    derived u32 (hi, lo) key planes are allocated)."""
    pytest.importorskip("jax")
    from seaweedfs_tpu.ops.index_kernel import IndexSnapshot

    idx = tmp_path / "1.idx"
    m = _small_map(idx, memtable=10, runs=2)
    for key in range(1, 41):
        m.put(key, key * 2, 100)
    m._flush_memtable()
    while len(m._runs) > 1:
        m._merge_smallest_adjacent()
    m._persist_manifest()
    keys, offs, sizes = m.snapshot()
    assert isinstance(offs, np.memmap) or isinstance(
        getattr(offs, "base", None), np.memmap
    )
    k64, _khi, _klo, off_u32, sizes_u32 = IndexSnapshot.prepare_host_columns(
        keys, offs, sizes
    )
    # dtype-matching columns are the SAME memory (no-op views)
    assert np.shares_memory(k64, keys)
    if offs.dtype == np.uint32:  # 5-byte-offset builds stay host-side
        assert np.shares_memory(off_u32, offs)
    assert np.shares_memory(sizes_u32, sizes)
    # and a full build over the sealed snapshot still answers correctly
    snap = IndexSnapshot(keys, offs, sizes)
    o, s, found = snap.lookup(np.array([3, 999], dtype=np.uint64))
    assert bool(found[0]) and not bool(found[1])
    assert int(o[0]) == 6 and int(s[0]) == 100
    m.close()


def test_lsm_put_batch_matches_sequential(tmp_path):
    """put_batch == the same puts applied one by one: identical idx
    bytes, identical state."""
    a = new_lsm_needle_map(str(tmp_path / "a.idx"))
    b = new_lsm_needle_map(str(tmp_path / "b.idx"))
    entries = [(k, k * 3 + 1, 100 + k) for k in range(1, 60)]
    entries += [(5, 777, 64)]  # overwrite inside the batch
    for k, o, s in entries:
        a.put(k, o, s)
    b.put_batch(entries)
    with open(tmp_path / "a.idx", "rb") as fa, open(
        tmp_path / "b.idx", "rb"
    ) as fb:
        assert fa.read() == fb.read()
    assert (a.file_count, a.content_size, a.deleted_size) == (
        b.file_count, b.content_size, b.deleted_size,
    )
    for k in range(1, 60):
        assert a.get(k) == b.get(k), k
    a.close()
    b.close()


def test_lsm_put_batch_flush_crossing_survives_crash(tmp_path):
    """Review fix: a put_batch that crosses the memtable limit must keep
    the snapshot manifest and the .idx log in lock-step — the flush
    fires AFTER the whole blob is appended, so a crash right after the
    batch (no close) reopens to exactly the dict-replay state."""
    idx = tmp_path / "1.idx"
    m = new_lsm_needle_map(str(idx))
    m.memtable_limit = 20
    m.max_runs = 3
    for k in range(1, 15):
        m.put(k, k * 2, 50)
    # one batch pushes the memtable well past the limit
    m.put_batch([(k, k * 3, 60) for k in range(15, 80)])
    assert len(m._mem) < 20  # the end-of-batch flush ran
    m._idx.close()  # crash: no save_snapshot
    m2 = load_lsm_needle_map(str(idx))
    _assert_matches_dict_replay(idx, m2, keyspace=90, tag="batch-flush")
    m2.close()


def test_charge_member_bytes_refunds_carrier_on_decline():
    """Review fix: a declined (over-quota) member's bytes must still be
    handed back to the carrier's bucket — sustained over-quota traffic
    from one tenant must not drain the default pool."""
    from seaweedfs_tpu.util.overload import AdmissionGate

    gate = AdmissionGate("refund", clock=lambda: 0.0)  # frozen: no refill
    gate.set_tenant_quota("carrier", byte_ps=1000.0, burst_s=1.0)
    gate.set_tenant_quota("member", byte_ps=100.0, burst_s=1.0)
    carrier_q = gate._tenants["carrier"].quota
    member_q = gate._tenants["member"].quota
    # the frame body was charged to the carrier at admission
    carrier_q.charge_bytes(400)
    before = carrier_q._bt
    # member over quota: decline, but the carrier gets its share back
    member_q._bt = -1e6
    assert gate.charge_member_bytes("member", 400, carrier="carrier") is False
    assert carrier_q._bt == before + 400
    # successful attribution refunds the carrier too and bills the member
    ok_before_member = member_q._bt = 100.0
    before = carrier_q._bt
    assert gate.charge_member_bytes("member", 80, carrier="carrier") is True
    assert member_q._bt == ok_before_member - 80
    assert carrier_q._bt == min(1000.0, before + 80)


def test_untenanted_rpc_exempt_from_quota():
    """Round-2 review fix: a drained default/wildcard byte bucket must
    never shed UNTENANTED gRPC calls — anonymous gRPC is the cluster's
    own control plane (repair/vacuum dispatch)."""
    from seaweedfs_tpu.util.overload import AdmissionGate

    gate = AdmissionGate("ctrl", clock=lambda: 0.0)
    gate.set_tenant_quota("default", byte_ps=10.0, burst_s=1.0)
    gate._tenants["default"].quota._bt = -1e9  # drained by HTTP traffic
    assert gate.charge_rpc_bytes(None, 1 << 20) is True
    gate.charge_rpc_response(None, 1 << 20)  # no-op, no crash
    # a named tenant still gets refused on the same gate
    gate.set_tenant_quota("t", byte_ps=10.0, burst_s=1.0)
    gate._tenants["t"].quota._bt = -1e9
    assert gate.charge_rpc_bytes("t", 100) is False


def test_charge_member_bytes_takes_request_token():
    """Round-2 review fix: the member pays its request token too —
    host-coalesced batching must not bypass a qps quota (each chunk was
    one volume request before coalescing)."""
    from seaweedfs_tpu.util.overload import AdmissionGate

    gate = AdmissionGate("tok", clock=lambda: 0.0)
    gate.set_tenant_quota("alice", qps=2.0, burst_s=1.0)
    assert gate.charge_member_bytes("alice", 10) is True
    assert gate.charge_member_bytes("alice", 10) is True
    # frozen clock: the two burst tokens are gone
    assert gate.charge_member_bytes("alice", 10) is False


def test_sqlite_put_batch_intra_batch_duplicate_metrics(tmp_path):
    """Round-2 review fix: SqliteNeedleMap.put_batch's deferred
    executemany must not blind the metric to intra-batch duplicate
    keys (the superseded copy's bytes feed the vacuum garbage ratio)."""
    from seaweedfs_tpu.storage.needle_map.disk_maps import SqliteNeedleMap

    a = SqliteNeedleMap(str(tmp_path / "a.idx"))
    b = SqliteNeedleMap(str(tmp_path / "b.idx"))
    entries = [(1, 10, 100), (2, 20, 200), (1, 30, 150)]
    for k, o, s in entries:
        a.put(k, o, s)
    b.put_batch(entries)
    assert (a.file_count, a.deleted_count, a.content_size, a.deleted_size) \
        == (b.file_count, b.deleted_count, b.content_size, b.deleted_size)
    assert b.deleted_size == 100  # the superseded first copy counted
    a.destroy()
    b.destroy()


# ---------------------------------------------------------- volume layer --


def _fill_volume(v, n, size=64, start=1):
    from seaweedfs_tpu.storage.needle import Needle

    blobs = {}
    for i in range(start, start + n):
        nd = Needle(cookie=0xC0, id=i, data=(b"%06d" % i) * (size // 6))
        v.write_needle(nd)
        blobs[i] = bytes(nd.data)
    return blobs


def test_volume_lsm_vacuum_commit_swap_and_stale_snapshot(tmp_path):
    """Vacuum commit under the lsm kind: the swap invalidates the
    persisted snapshot, reads stay byte-identical, the next mount uses
    a FRESH snapshot — and the crash window where the OLD snapshot
    survives the renames is closed by the manifest binding."""
    from seaweedfs_tpu.storage import vacuum as vac
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    d = tmp_path / "vol"
    d.mkdir()
    v = Volume(str(d), "", 9, needle_map_kind="lsm")
    blobs = _fill_volume(v, 120)
    for i in range(1, 120, 3):
        v.delete_needle(Needle(cookie=0xC0, id=i))
        del blobs[i]
    base = v.file_name()
    # persist a snapshot of the PRE-vacuum log, stash it (the crash
    # window artifact), then vacuum
    v.nm.save_snapshot()
    stash = tmp_path / "stash"
    stash.mkdir()
    side = [
        fn
        for fn in os.listdir(d)
        if ".nmr-" in fn or fn.endswith(MANIFEST_EXT)
    ]
    for fn in side:
        shutil.copy2(d / fn, stash / fn)
    vac.compact2(v)
    v2 = vac.commit_compact(v)
    assert v2.needle_map_kind == "lsm"
    for i, data in blobs.items():
        assert bytes(v2.read_needle_by_key(i).data) == data, i
    v2.close()
    # normal remount: fresh snapshot, correct
    v3 = Volume(str(d), "", 9, create=False, needle_map_kind="lsm")
    assert v3.nm.loaded_from_snapshot
    assert v3.file_count() == len(blobs)
    v3.close()
    # crash window: restore the PRE-vacuum snapshot files over the
    # post-vacuum idx — load must reject them and still serve right
    for fn in os.listdir(d):
        if ".nmr-" in fn or fn.endswith(MANIFEST_EXT):
            os.remove(d / fn)
    for fn in side:
        shutil.copy2(stash / fn, d / fn)
    v4 = Volume(str(d), "", 9, create=False, needle_map_kind="lsm")
    for i, data in blobs.items():
        assert bytes(v4.read_needle_by_key(i).data) == data, i
    assert v4.file_count() == len(blobs)
    v4.destroy()


def test_volume_lsm_tail_sync_then_remount(tmp_path):
    """apply_incremental (the VolumeTailSync worker) replays pulled
    records through the lsm map's put/delete — the snapshot stays a
    valid prefix and the next mount is still snapshot+tail."""
    from seaweedfs_tpu.storage.volume import Volume
    from seaweedfs_tpu.storage.volume_backup import (
        apply_incremental,
        incremental_changes,
    )

    src_d = tmp_path / "src"
    rep_d = tmp_path / "rep"
    src_d.mkdir()
    rep_d.mkdir()
    src = Volume(str(src_d), "", 4, needle_map_kind="memory")
    blobs = _fill_volume(src, 40)
    src.sync()
    # replica = file copy of the prefix, mounted lsm
    for ext in (".dat", ".idx"):
        shutil.copy2(src.file_name() + ext, str(rep_d / ("4" + ext)))
    rep = Volume(str(rep_d), "", 4, create=False, needle_map_kind="lsm")
    rep.nm.save_snapshot()
    since = rep.last_append_at_ns
    blobs.update(_fill_volume(src, 25, start=100))
    data = b"".join(incremental_changes(src, since))
    applied = apply_incremental(rep, data)
    assert applied == 25
    for i, d_ in blobs.items():
        assert bytes(rep.read_needle_by_key(i).data) == d_, i
    rep.close()
    rep2 = Volume(str(rep_d), "", 4, create=False, needle_map_kind="lsm")
    assert rep2.nm.loaded_from_snapshot
    for i, d_ in blobs.items():
        assert bytes(rep2.read_needle_by_key(i).data) == d_, i
    rep2.close()
    src.close()


def test_volume_write_needle_batch_one_extent(tmp_path):
    """write_needle_batch: byte-identical reads vs the single-needle
    path on a twin volume, identical .idx entry streams, per-item
    errors isolated (a cookie mismatch fails its slot only)."""
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import CookieMismatch, Volume

    da, db = tmp_path / "a", tmp_path / "b"
    da.mkdir()
    db.mkdir()
    va = Volume(str(da), "", 2, needle_map_kind="lsm")
    vb = Volume(str(db), "", 2, needle_map_kind="lsm")
    payloads = {i: os.urandom(200 + i) for i in range(1, 30)}
    for i, p in payloads.items():
        va.write_needle(Needle(cookie=0xAB, id=i, data=p))
    res = vb.write_needle_batch(
        [Needle(cookie=0xAB, id=i, data=p) for i, p in payloads.items()]
    )
    assert all(not isinstance(r, Exception) for r in res)
    for i, p in payloads.items():
        assert bytes(va.read_needle_by_key(i).data) == p
        assert bytes(vb.read_needle_by_key(i).data) == p
    with open(va.file_name() + ".idx", "rb") as fa, open(
        vb.file_name() + ".idx", "rb"
    ) as fb:
        assert fa.read() == fb.read()
    # mixed batch: one slot fails its cookie check, the rest land
    res = vb.write_needle_batch(
        [
            Needle(cookie=0xAB, id=1, data=b"updated-1"),
            Needle(cookie=0xEE, id=2, data=b"wrong-cookie"),
            Needle(cookie=0xAB, id=3, data=b"updated-3"),
        ]
    )
    assert isinstance(res[1], CookieMismatch)
    assert not isinstance(res[0], Exception)
    assert not isinstance(res[2], Exception)
    assert bytes(vb.read_needle_by_key(1).data) == b"updated-1"
    assert bytes(vb.read_needle_by_key(2).data) == payloads[2]
    assert bytes(vb.read_needle_by_key(3).data) == b"updated-3"
    va.destroy()
    vb.destroy()


# ---------------------------------------------------------- server layer --


def test_batch_put_tenant_tagged_frame_e2e(tmp_path, monkeypatch):
    """The tenant-tagged `!batch/put` frame through a live volume
    server: one frame carries two tenants' needles; both land through
    the group-commit coalesced path byte-identically, each member's
    bytes are re-attributed to its OWN principal (heat/quota state
    exists per member), and a member over its byte quota declines
    item-wise with err='quota' while the rest of the frame lands."""
    import json
    import struct

    monkeypatch.setenv("SEAWEEDFS_TPU_ADMIT", "1")
    from test_cluster import Cluster, assign_retry

    from seaweedfs_tpu.util.fasthttp import FastHTTPClient

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        http = FastHTTPClient()
        try:
            ar = await assign_retry(cluster.master.address)
            vs = cluster.volume_servers[0]
            vid = int(ar.fid.split(",")[0])
            fids = [ar.fid] + [f"{ar.fid}_{i}" for i in range(1, 8)]
            tenants = ["alice", "bob", "alice", "bob", "", "alice",
                       "bob", "alice"]
            payloads = [os.urandom(300 + i) for i in range(8)]
            parts = [struct.pack("<I", len(fids) | 0x80000000)]
            for fid, tenant, payload in zip(fids, tenants, payloads):
                fb = fid.encode()
                tb = tenant.encode()
                parts.append(
                    struct.pack("<HHI", len(fb), len(tb), len(payload))
                )
                parts += [fb, tb, payload]
            st, resp = await http.request(
                "POST", vs.address, "/!batch/put",
                body=b"".join(parts),
                content_type="application/octet-stream",
            )
            assert st == 200, resp
            out = json.loads(resp)
            assert all("err" not in r for r in out), out
            for fid, payload in zip(fids, payloads):
                st, got = await http.request("GET", vs.address, "/" + fid)
                assert st == 200 and got == payload, fid
            # member principals were attributed at the volume gate
            gate = vs._core.gate
            assert "alice" in gate._tenants and "bob" in gate._tenants
            # a member whose byte quota is dry declines item-wise
            gate.set_tenant_quota("broke", byte_ps=1.0, burst_s=1.0)
            gate._tenants["broke"].quota._bt = -10_000.0
            parts = [struct.pack("<I", 2 | 0x80000000)]
            refused_fid = f"{ar.fid}_20"
            accepted_fid = f"{ar.fid}_21"
            for fid, tenant, payload in (
                (refused_fid, "broke", b"refused-bytes"),
                (accepted_fid, "alice", b"accepted-bytes"),
            ):
                fb, tb = fid.encode(), tenant.encode()
                parts.append(
                    struct.pack("<HHI", len(fb), len(tb), len(payload))
                )
                parts += [fb, tb, payload]
            st, resp = await http.request(
                "POST", vs.address, "/!batch/put",
                body=b"".join(parts),
                content_type="application/octet-stream",
            )
            assert st == 200
            out = {r["f"]: r for r in json.loads(resp)}
            assert out[refused_fid].get("err") == "quota"
            assert "err" not in out[accepted_fid]
            # the quota shed was counted against the member
            assert gate._tenants["broke"].shed >= 1
            # group commit actually carried frames (coalesced appends)
            gc = vs._group_committers.get(vid)
            assert gc is not None and gc.stats["batches"] >= 1
        finally:
            await http.close()
            await cluster.stop()

    asyncio.run(body())


def test_grpc_byte_quota_seam(tmp_path):
    """gRPC per-tenant byte quota (pb/rpc.py handler seam): a unary
    call whose caller tenant is over its byte bucket aborts
    RESOURCE_EXHAUSTED in the handler wrapper (no handler work), the
    shed is counted class='rpc' reason='quota', and response bytes
    charge the bucket at completion."""
    import grpc

    from test_cluster import free_port

    from seaweedfs_tpu.pb.rpc import Service, Stub, serve
    from seaweedfs_tpu.util import tenancy
    from seaweedfs_tpu.util.overload import AdmissionGate

    async def body():
        gate = AdmissionGate("rpcquota")
        gate.set_tenant_quota("metered", byte_ps=50.0, burst_s=1.0)
        svc = Service("volume", gate=gate)
        calls = []

        @svc.unary("Echo")
        async def _echo(req, context):
            calls.append(req)
            return {"echo": req.get("blob", b"")}

        addr = f"127.0.0.1:{free_port()}"
        server = await serve(addr, svc)
        from seaweedfs_tpu.pb.rpc import new_channel

        ch = new_channel(addr)
        stub = Stub(addr, "volume", channel=ch)
        try:
            tok = tenancy.set_current("metered")
            try:
                out = await stub.call("Echo", {"blob": b"x" * 100})
                assert out["echo"] == b"x" * 100
                # drain the bucket: response+request bytes charged; a
                # following oversized message must be refused
                gate._tenants["metered"].quota._bt = -100_000.0
                with pytest.raises(grpc.aio.AioRpcError) as ei:
                    await stub.call("Echo", {"blob": b"y" * 100})
                assert (
                    ei.value.code()
                    == grpc.StatusCode.RESOURCE_EXHAUSTED
                )
            finally:
                tenancy.reset_current(tok)
            assert len(calls) == 1  # the refused call never ran
            assert gate._tenants["metered"].shed >= 1
            # an unmetered tenant sails through the same seam
            out = await stub.call("Echo", {"blob": b"z" * 50})
            assert out["echo"] == b"z" * 50
            # review fix: a non-ASCII tenant name must not hard-fail
            # the RPC (metadata travels percent-encoded) and must
            # round-trip exactly into the handler-side gate state
            tok = tenancy.set_current("café-50%off")
            try:
                out = await stub.call("Echo", {"blob": b"q"})
                assert out["echo"] == b"q"
            finally:
                tenancy.reset_current(tok)
            assert "café-50%off" in gate._tenants
        finally:
            await ch.close()
            await server.stop(0.2)

    asyncio.run(body())
