import pytest

from seaweedfs_tpu import types as t
from seaweedfs_tpu.storage.file_id import FileId, format_needle_id_cookie
from seaweedfs_tpu.storage.ttl import TTL, EMPTY_TTL
from seaweedfs_tpu.util.crc import CRC, masked_crc


def test_endian_codecs():
    assert t.u64_to_bytes(0x0102030405060708) == bytes(range(1, 9))
    assert t.bytes_to_u64(bytes(range(1, 9))) == 0x0102030405060708
    assert t.u32_to_bytes(0xDEADBEEF) == b"\xde\xad\xbe\xef"
    assert t.bytes_to_u32(b"\xde\xad\xbe\xef") == 0xDEADBEEF
    assert t.u16_to_bytes(0x0102) == b"\x01\x02"
    assert t.bytes_to_u16(b"\x01\x02") == 0x0102


def test_offset_units_roundtrip():
    for actual in [0, 8, 16, 1024, t.MAX_POSSIBLE_VOLUME_SIZE - 8]:
        units = t.to_offset_units(actual)
        b = t.offset_to_bytes(units)
        assert len(b) == t.OFFSET_SIZE
        assert t.to_actual_offset(t.bytes_to_offset(b)) == actual


def test_constants_match_reference():
    # ref: weed/storage/types/needle_types.go:24-32
    assert t.NEEDLE_HEADER_SIZE == 16
    assert t.NEEDLE_MAP_ENTRY_SIZE == 16
    assert t.NEEDLE_PADDING_SIZE == 8
    assert t.TOMBSTONE_FILE_SIZE == 0xFFFFFFFF
    assert t.MAX_POSSIBLE_VOLUME_SIZE == 32 * 1024**3


def test_crc_masked_known_value():
    # CRC32C("123456789") = 0xE3069283; masked per crc.go Value()
    raw = 0xE3069283
    assert CRC(raw).raw == raw
    expected = (((raw >> 15) | (raw << 17)) + 0xA282EAD8) & 0xFFFFFFFF
    assert masked_crc(b"123456789") == expected


def test_crc_incremental():
    whole = CRC(0).update(b"hello world")
    parts = CRC(0).update(b"hello ").update(b"world")
    assert whole.raw == parts.raw


def test_ttl_roundtrip():
    for s in ["3m", "4h", "5d", "6w", "7M", "8y", "90"]:
        ttl = TTL.read(s)
        assert TTL.from_bytes(ttl.to_bytes()) == ttl
        assert TTL.from_u32(ttl.to_u32()) == ttl
    assert TTL.read("") is EMPTY_TTL
    assert TTL.read("90") == TTL(count=90, unit=1)
    assert str(TTL.read("3m")) == "3m"
    assert TTL.from_bytes(b"\x00\x00") is EMPTY_TTL


def test_file_id_format():
    # leading zero bytes trimmed (ref file_id.go:63-73)
    assert format_needle_id_cookie(1, 0x12345678) == "0112345678"
    fid = FileId(volume_id=3, key=0x1234, cookie=0xABCD1234)
    s = str(fid)
    assert s.startswith("3,")
    parsed = FileId.parse(s)
    assert parsed == fid


def test_file_id_parse_errors():
    with pytest.raises(ValueError):
        FileId.parse("no-comma")
    with pytest.raises(ValueError):
        FileId.parse(",123")
