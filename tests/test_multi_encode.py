"""Batched multi-volume EC encode (BASELINE config #3): write_ec_files_multi
byte-parity vs the per-volume pipeline, and the VolumeEcShardsGenerateBatch
RPC end-to-end (ref per-volume semantics: ec_encoder.go:57,120-136)."""

import asyncio
import os
import random

import aiohttp
import numpy as np
import pytest

from seaweedfs_tpu.storage.erasure_coding import (
    to_ext,
    write_ec_files,
    write_ec_files_multi,
)
from seaweedfs_tpu.storage.erasure_coding.coder_cpu import CpuRSCodec

LARGE, SMALL = 8192, 1024


def _mk_dat(path: str, size: int) -> None:
    data = np.random.default_rng(size + 7).integers(
        0, 256, size, dtype=np.uint8
    )
    with open(path, "wb") as f:
        f.write(data.tobytes())


def _shards(base: str) -> list:
    out = []
    for i in range(14):
        with open(base + to_ext(i), "rb") as f:
            out.append(f.read())
    return out


def test_multi_device_batch_path_matches_oracle(tmp_path):
    """The shared-wide-batch streaming path (is_device codecs) must be
    byte-identical to per-volume encodes across mixed geometries."""
    from seaweedfs_tpu.ops.rs_kernel import TpuRSCodec

    sizes = [
        LARGE * 10 * 2 + SMALL * 10 * 2 + 333,
        SMALL * 10 * 5,
        SMALL * 3 + 17,
        0,
        LARGE * 10 + 1,
    ]
    singles, multis = [], []
    for j, size in enumerate(sizes):
        for sub, acc in (("ds", singles), ("dm", multis)):
            d = tmp_path / f"{sub}{j}"
            d.mkdir()
            _mk_dat(str(d / "1.dat"), size)
            acc.append(str(d / "1"))
    for base in singles:
        write_ec_files(
            base, codec=CpuRSCodec(),
            large_block_size=LARGE, small_block_size=SMALL,
        )
    codec = TpuRSCodec()
    assert getattr(codec, "is_device", False)
    write_ec_files_multi(
        multis, codec=codec,
        large_block_size=LARGE, small_block_size=SMALL,
    )
    for s, m, size in zip(singles, multis, sizes):
        assert _shards(m) == _shards(s), size


def test_multi_matches_per_volume_oracle(tmp_path):
    # varied geometries: large+small rows, small-only, sub-row tail, empty
    sizes = [
        LARGE * 10 * 2 + SMALL * 10 * 2 + 333,
        SMALL * 10 * 5,
        SMALL * 3 + 17,
        0,
        LARGE * 10 + 1,
    ]
    singles, multis = [], []
    for j, size in enumerate(sizes):
        for sub, acc in (("s", singles), ("m", multis)):
            d = tmp_path / f"{sub}{j}"
            d.mkdir()
            _mk_dat(str(d / "1.dat"), size)
            acc.append(str(d / "1"))
    codec = CpuRSCodec()
    for base in singles:
        write_ec_files(
            base, codec=codec,
            large_block_size=LARGE, small_block_size=SMALL,
        )
    write_ec_files_multi(
        multis, codec=codec,
        large_block_size=LARGE, small_block_size=SMALL,
    )
    for s, m, size in zip(singles, multis, sizes):
        assert _shards(m) == _shards(s), size


def test_multi_with_native_codec(tmp_path):
    native = pytest.importorskip("seaweedfs_tpu.native")
    if not native.available():
        pytest.skip("native gf256 library unavailable")
    from seaweedfs_tpu.storage.erasure_coding.coder_native import NativeRSCodec

    sizes = [SMALL * 10 * 3 + 100, SMALL * 10 * 3 + 100, SMALL * 2]
    oracle, multis = [], []
    for j, size in enumerate(sizes):
        for sub, acc in (("o", oracle), ("m", multis)):
            d = tmp_path / f"{sub}{j}"
            d.mkdir()
            _mk_dat(str(d / "1.dat"), size)
            acc.append(str(d / "1"))
    for base in oracle:
        write_ec_files(
            base, codec=CpuRSCodec(),
            large_block_size=LARGE, small_block_size=SMALL,
        )
    write_ec_files_multi(
        multis, codec=NativeRSCodec(),
        large_block_size=LARGE, small_block_size=SMALL, workers=3,
    )
    for o, m in zip(oracle, multis):
        assert _shards(m) == _shards(o)


def test_shell_ec_encode_batches_colocated_volumes(tmp_path):
    """`ec.encode -volumeId a,b` with both volumes on one node goes through
    VolumeEcShardsGenerateBatch, then spreads and serves reads as usual."""
    from seaweedfs_tpu.pb.rpc import close_all_channels
    from seaweedfs_tpu.shell import CommandEnv, run_command
    from seaweedfs_tpu.storage.file_id import format_needle_id_cookie

    from tests.test_cluster import Cluster
    from seaweedfs_tpu.client import assign
    from seaweedfs_tpu.client.operation import read_url, upload_data

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        try:
            async with aiohttp.ClientSession() as session:
                from tests.test_cluster import assign_retry
                ar0 = await assign_retry(cluster.master.address)
                url = ar0.url
                vid0 = int(ar0.fid.split(",")[0])
                # a second volume that is KNOWN to exist on the (single)
                # server: assign may hand out the highest-numbered volume,
                # where vid0 + 1 was never grown
                vids = [vid0, vid0 - 1 if vid0 > 1 else vid0 + 1]
                payloads = {}
                for vid in vids:
                    for i in range(1, 6):
                        fid = f"{vid},{format_needle_id_cookie(i, 0xEE00 + i)}"
                        data = random.randbytes(1200 + 17 * i)
                        await upload_data(session, url, fid, data)
                        payloads[fid] = data

                env = CommandEnv(cluster.master.address)
                for _ in range(100):
                    nodes = await env.collect_data_nodes()
                    have = {
                        int(v["id"])
                        for dn in nodes
                        for v in dn.get("volumes", [])
                    }
                    if set(vids) <= have:
                        break
                    await asyncio.sleep(0.1)
                assert (await run_command(env, "lock")) == "locked"
                out = await run_command(
                    env, f"ec.encode -volumeId {vids[0]},{vids[1]}"
                )
                assert out.count("encoded") == 2, out

                for fid, want in payloads.items():
                    got = await read_url(session, f"http://{url}/{fid}")
                    assert got == want, fid
        finally:
            await cluster.stop()
            await close_all_channels()

    asyncio.run(body())


def test_generate_batch_rpc_and_read_back(tmp_path):
    from seaweedfs_tpu.pb import grpc_address
    from seaweedfs_tpu.pb.rpc import Stub, close_all_channels
    from seaweedfs_tpu.storage.file_id import format_needle_id_cookie

    from tests.test_cluster import Cluster
    from seaweedfs_tpu.client import assign
    from seaweedfs_tpu.client.operation import read_url, upload_data

    async def body():
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        try:
            async with aiohttp.ClientSession() as session:
                from tests.test_cluster import assign_retry
                ar0 = await assign_retry(cluster.master.address)
                url = ar0.url
                vid0 = int(ar0.fid.split(",")[0])
                # see test_shell_ec_encode_batches_colocated_volumes: vid0+1
                # need not exist when assign picked the highest-grown volume
                vids = [vid0, vid0 - 1 if vid0 > 1 else vid0 + 1]
                payloads = {}
                for vid in vids:
                    for i in range(1, 8):
                        fid = f"{vid},{format_needle_id_cookie(i, 0xCD00 + i)}"
                        data = random.randbytes(1500 + 31 * i)
                        await upload_data(session, url, fid, data)
                        payloads[fid] = data

                stub = Stub(grpc_address(url), "volume")
                for vid in vids:
                    await stub.call("VolumeMarkReadonly", {"volume_id": vid})
                r = await stub.call(
                    "VolumeEcShardsGenerateBatch",
                    {"volume_ids": vids},
                    timeout=120,
                )
                assert not r.get("error"), r
                assert not r.get("errors"), r

                # serve from EC shards only: mount, drop the plain volumes
                for vid in vids:
                    r = await stub.call(
                        "VolumeEcShardsMount",
                        {"volume_id": vid, "shard_ids": list(range(14))},
                    )
                    assert not r.get("error"), r
                    await stub.call("VolumeUnmount", {"volume_id": vid})
                    await stub.call("VolumeDelete", {"volume_id": vid})
                for fid, want in payloads.items():
                    got = await read_url(session, f"http://{url}/{fid}")
                    assert got == want, fid
        finally:
            await cluster.stop()
            await close_all_channels()

    asyncio.run(body())
