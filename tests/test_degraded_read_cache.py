"""Degraded-read fast path (ISSUE 3): concurrent survivor fetches, the
reconstructed-interval cache, its .ecj-delete invalidation, and the
cold-vs-cache-hit split of seaweedfs_tpu_ec_reconstructions_total.

The harness drives EcHandlers._recover_one_interval directly against a
real on-disk EC volume; "remote" shard holders are a fault-injection seam
that reads the real shard bytes after an injected latency."""

import asyncio
import time

import numpy as np

from seaweedfs_tpu.server.volume_ec import (
    DegradedIntervalCache,
    EC_DEGRADED_SPAN,
    EcHandlers,
)
from seaweedfs_tpu.storage.erasure_coding import to_ext, write_ec_files
from seaweedfs_tpu.storage.erasure_coding.coder_cpu import CpuRSCodec
from seaweedfs_tpu.storage.erasure_coding.ec_volume import (
    EcVolume,
    EcVolumeShard,
)
from seaweedfs_tpu.storage.idx import entry_to_bytes
from seaweedfs_tpu.util.metrics import EC_RECONSTRUCTIONS


class _Host(EcHandlers):
    """Just enough VolumeServer surface for the degraded-read path."""

    address = "127.0.0.1:0"
    public_url = "localhost:0"
    codec = CpuRSCodec()
    codec_backend = "numpy"

    def __init__(self, store=None):
        self.store = store


class _Store:
    def __init__(self, ev):
        self._ev = ev

    def find_ec_volume(self, vid):
        return self._ev


def _reconstruction_counts() -> dict:
    with EC_RECONSTRUCTIONS._lock:
        return {
            dict(k).get("kind", ""): v
            for k, v in EC_RECONSTRUCTIONS._values.items()
        }


def _make_ec_volume(tmp_path, vid=1, needle_key=7):
    """Real shard files + a 1-entry .ecx so EcVolume loads and deletes."""
    base = str(tmp_path / str(vid))
    rng = np.random.default_rng(5)
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, size=1 << 20, dtype=np.uint8).tobytes())
    write_ec_files(base)
    with open(base + ".ecx", "wb") as f:
        f.write(entry_to_bytes(needle_key, 1, 100))
    ev = EcVolume(str(tmp_path), "", vid)
    return base, ev


def test_survivor_fetches_are_concurrent(tmp_path):
    """Fault-injected latency on every remote survivor read: the recover
    wall must track the SLOWEST survivor, not the sum of 13 of them."""
    base, ev = _make_ec_volume(tmp_path)
    host = _Host()
    delay = 0.05
    calls = []

    async def injected_remote_read(ev_, shard_id, offset, size, key, deadline=None):
        calls.append(shard_id)
        await asyncio.sleep(delay)  # injected network latency
        with open(base + to_ext(shard_id), "rb") as f:
            f.seek(offset)
            return f.read(size)

    host._read_remote_shard_interval = injected_remote_read

    async def body():
        t0 = time.perf_counter()
        out = await host._recover_one_interval(ev, 3, 4096, 1024, 0)
        return out, time.perf_counter() - t0

    out, wall = asyncio.run(body())
    with open(base + to_ext(3), "rb") as f:
        f.seek(4096)
        assert out == f.read(1024)
    # remote fetch amplification is trimmed: only k+1 holders are asked
    # (one spare), in ONE gather — not all 13 candidates
    assert len(calls) == ev.data_shards + 1
    # serial would be >= 11 * delay = 0.55s; concurrent ~= one delay
    assert wall < 7 * delay, f"survivor fetches look serialized: {wall:.3f}s"
    ev.close()


def test_degraded_cache_hit_and_counters(tmp_path):
    """Repeat reads of a dead shard come from the interval cache with the
    same bytes as a cold reconstruct, and the reconstruction counter
    distinguishes the two kinds."""
    base, ev = _make_ec_volume(tmp_path)
    # mount every shard EXCEPT the dead one locally
    dead = 2
    for i in range(14):
        if i != dead:
            ev.add_shard(EcVolumeShard(str(tmp_path), "", 1, i))
    host = _Host()

    async def no_remote(*a, **kw):
        return None

    host._read_remote_shard_interval = no_remote
    before = _reconstruction_counts()

    off, size = 3 * EC_DEGRADED_SPAN + 513, 2048
    cold = asyncio.run(host._recover_one_interval(ev, dead, off, size, 0))
    with open(base + to_ext(dead), "rb") as f:
        f.seek(off)
        assert cold == f.read(size)
    mid = _reconstruction_counts()
    assert mid.get("cold", 0) == before.get("cold", 0) + 1

    hit = asyncio.run(host._recover_one_interval(ev, dead, off, size, 0))
    assert hit == cold
    # readahead: a neighbouring interval in the same span is a hit too
    near = asyncio.run(host._recover_one_interval(ev, dead, off + size, 512, 0))
    with open(base + to_ext(dead), "rb") as f:
        f.seek(off + size)
        assert near == f.read(512)
    after = _reconstruction_counts()
    assert after.get("cold", 0) == mid.get("cold", 0)  # no new cold decode
    assert after.get("cache_hit", 0) == before.get("cache_hit", 0) + 2
    ev.close()


def test_ecj_delete_invalidates_cache(tmp_path):
    """A blob delete (tombstone -> .ecj) drops the volume's cached spans:
    the next degraded read pays a cold reconstruct again."""
    base, ev = _make_ec_volume(tmp_path, needle_key=7)
    dead = 5
    for i in range(14):
        if i != dead:
            ev.add_shard(EcVolumeShard(str(tmp_path), "", 1, i))
    host = _Host(store=_Store(ev))

    async def no_remote(*a, **kw):
        return None

    host._read_remote_shard_interval = no_remote
    asyncio.run(host._recover_one_interval(ev, dead, 0, 1024, 0))
    assert len(host._ec_degraded_cache()) == 1

    asyncio.run(
        host._grpc_ec_blob_delete({"volume_id": 1, "file_key": 7}, None)
    )
    assert len(host._ec_degraded_cache()) == 0
    before = _reconstruction_counts()
    asyncio.run(host._recover_one_interval(ev, dead, 0, 1024, 0))
    assert (
        _reconstruction_counts().get("cold", 0) == before.get("cold", 0) + 1
    )
    ev.close()


def test_interval_cache_capacity_bounded():
    cache = DegradedIntervalCache(capacity_bytes=4 * EC_DEGRADED_SPAN)
    span = bytes(EC_DEGRADED_SPAN)
    for i in range(32):
        cache.put(1, 0, i * EC_DEGRADED_SPAN, span)
        assert len(cache) <= 4
    # most-recent spans survive
    assert (
        cache.get(1, 0, 31 * EC_DEGRADED_SPAN, 16) == span[:16]
    )
    assert cache.get(1, 0, 0, 16) is None


def test_interval_cache_span_alignment():
    # unknown shard size: exact span, no readahead
    assert DegradedIntervalCache.span_for(1000, 64, None) == (1000, 64)
    # aligned span within the shard
    start, size = DegradedIntervalCache.span_for(
        EC_DEGRADED_SPAN + 5, 64, 10 * EC_DEGRADED_SPAN
    )
    assert start == EC_DEGRADED_SPAN and size == EC_DEGRADED_SPAN
    # tail capped at shard size
    start, size = DegradedIntervalCache.span_for(
        9 * EC_DEGRADED_SPAN + 5, 64, 9 * EC_DEGRADED_SPAN + 100
    )
    assert start + size == 9 * EC_DEGRADED_SPAN + 100
