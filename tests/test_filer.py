import pytest

from seaweedfs_tpu.filer import (
    Entry,
    FileChunk,
    Filer,
    MemoryFilerStore,
    LogFilerStore,
    SqliteFilerStore,
    non_overlapping_visible_intervals,
    read_from_visible_intervals,
    total_size,
)
from seaweedfs_tpu.filer.filechunks import view_from_visibles


def chunk(fid, offset, size, mtime):
    return FileChunk(fid=fid, offset=offset, size=size, mtime_ns=mtime)


# ---------- chunk visibility (ref filer2/filechunks_test.go) ----------
def test_visibles_single_chunk():
    vis = non_overlapping_visible_intervals([chunk("a", 0, 100, 1)])
    assert len(vis) == 1
    assert (vis[0].start, vis[0].stop, vis[0].fid) == (0, 100, "a")


def test_visibles_newest_wins_full_overwrite():
    vis = non_overlapping_visible_intervals(
        [chunk("a", 0, 100, 1), chunk("b", 0, 100, 2)]
    )
    assert len(vis) == 1
    assert vis[0].fid == "b"


def test_visibles_partial_overwrite():
    vis = non_overlapping_visible_intervals(
        [chunk("a", 0, 100, 1), chunk("b", 50, 100, 2)]
    )
    assert [(v.start, v.stop, v.fid) for v in vis] == [
        (0, 50, "a"),
        (50, 150, "b"),
    ]


def test_visibles_middle_overwrite_splits():
    vis = non_overlapping_visible_intervals(
        [chunk("a", 0, 300, 1), chunk("b", 100, 50, 2)]
    )
    assert [(v.start, v.stop, v.fid) for v in vis] == [
        (0, 100, "a"),
        (100, 150, "b"),
        (150, 300, "a"),
    ]


def test_visibles_disjoint_with_hole():
    vis = non_overlapping_visible_intervals(
        [chunk("a", 0, 100, 1), chunk("b", 200, 100, 1)]
    )
    assert [(v.start, v.stop) for v in vis] == [(0, 100), (200, 300)]
    assert total_size([chunk("a", 0, 100, 1), chunk("b", 200, 100, 1)]) == 300


def test_read_from_visibles_assembles_and_zero_fills():
    blobs = {"a": bytes(range(100)), "b": bytes(reversed(range(100)))}
    chunks = [chunk("a", 0, 100, 1), chunk("b", 200, 100, 1)]
    vis = non_overlapping_visible_intervals(chunks)
    out = read_from_visible_intervals(vis, blobs.__getitem__, 50, 200)
    assert out[:50] == bytes(range(50, 100))
    assert out[50:150] == b"\x00" * 100
    assert out[150:200] == bytes(reversed(range(100)))[:50]


def test_view_from_visibles_offsets_into_chunks():
    chunks = [chunk("a", 0, 100, 1), chunk("b", 50, 100, 2)]
    vis = non_overlapping_visible_intervals(chunks)
    views = view_from_visibles(vis, 60, 30)
    assert len(views) == 1
    assert views[0].fid == "b"
    assert views[0].offset_in_chunk == 10
    assert views[0].size == 30


# ---------- filer + stores ----------
def _fresh_log_store():
    import os
    import tempfile

    return LogFilerStore(os.path.join(tempfile.mkdtemp(), "meta.flog"))


def _fresh_lsm_store():
    import tempfile

    from seaweedfs_tpu.filer.lsm_store import LsmFilerStore

    # tiny memtable + low segment cap so ordinary tests exercise flush and
    # compaction, not just the memtable
    return LsmFilerStore(tempfile.mkdtemp(), memtable_limit=4, max_segments=2)



@pytest.mark.parametrize(
    "store_cls",
    [MemoryFilerStore, SqliteFilerStore, _fresh_log_store, _fresh_lsm_store],
)
def test_filer_crud_and_tree(store_cls):
    f = Filer(store_cls())
    f.touch("/docs/readme.txt", "text/plain", [chunk("1,ab", 0, 10, 1)])
    f.touch("/docs/sub/inner.bin", "", [chunk("2,cd", 0, 20, 1)])

    e = f.find_entry("/docs/readme.txt")
    assert e is not None and e.size() == 10
    d = f.find_entry("/docs")
    assert d is not None and d.is_directory

    listing = f.list_entries("/docs")
    assert [e.name for e in listing] == ["readme.txt", "sub"]

    # rename a directory subtree
    f.rename("/docs", "/archive")
    assert f.find_entry("/docs/readme.txt") is None
    assert f.find_entry("/archive/readme.txt") is not None
    assert f.find_entry("/archive/sub/inner.bin") is not None

    # refuse non-recursive delete of a non-empty dir
    with pytest.raises(OSError):
        f.delete_entry("/archive")
    deleted_chunks = f.delete_entry("/archive", recursive=True)
    assert {c.fid for c in deleted_chunks} == {"1,ab", "2,cd"}
    assert f.find_entry("/archive/readme.txt") is None


@pytest.mark.parametrize(
    "store_cls",
    [MemoryFilerStore, SqliteFilerStore, _fresh_log_store, _fresh_lsm_store],
)
def test_filer_overwrite_collects_old_chunks(store_cls):
    collected = []
    f = Filer(store_cls(), on_delete_chunks=collected.extend)
    f.touch("/a.txt", "", [chunk("1,aa", 0, 5, 1)])
    f.touch("/a.txt", "", [chunk("2,bb", 0, 7, 2)])
    assert collected == ["1,aa"]


@pytest.mark.parametrize(
    "store_cls",
    [MemoryFilerStore, SqliteFilerStore, _fresh_log_store, _fresh_lsm_store],
)
def test_filer_file_blocks_subdirectory(store_cls):
    f = Filer(store_cls())
    f.touch("/x", "", [])
    with pytest.raises(NotADirectoryError):
        f.touch("/x/y", "", [])


@pytest.mark.parametrize(
    "store_cls",
    [MemoryFilerStore, SqliteFilerStore, _fresh_log_store, _fresh_lsm_store],
)
def test_store_pagination(store_cls):
    f = Filer(store_cls())
    for i in range(25):
        f.touch(f"/dir/f{i:03d}", "", [])
    page1 = f.list_entries("/dir", limit=10)
    assert len(page1) == 10
    page2 = f.list_entries("/dir", start_file_name=page1[-1].name, inclusive=False, limit=10)
    assert len(page2) == 10
    assert page1[-1].name < page2[0].name
    page3 = f.list_entries("/dir", start_file_name=page2[-1].name, inclusive=False, limit=10)
    assert len(page3) == 5


def test_log_store_survives_reopen(tmp_path):
    """The WAL store replays its log and compacts on open
    (the leveldb2-class durability role)."""
    import os

    path = str(tmp_path / "meta.flog")
    store = LogFilerStore(path)
    f = Filer(store)
    f.touch("/keep/a.txt", "", [chunk("1,ab", 0, 10, 1)])
    f.touch("/keep/b.txt", "", [chunk("2,cd", 0, 20, 1)])
    f.delete_entry("/keep/b.txt")
    store.close()

    store2 = LogFilerStore(path)
    f2 = Filer(store2)
    assert f2.find_entry("/keep/a.txt") is not None
    assert f2.find_entry("/keep/b.txt") is None
    assert [e.name for e in f2.list_entries("/keep")] == ["a.txt"]

    # compaction rewrote the log to live entries only: reopening after many
    # overwrites keeps it bounded
    for i in range(50):
        f2.touch("/keep/a.txt", "", [chunk(f"3,{i:02x}", 0, 5, i + 10)])
    size_before = os.path.getsize(path)
    store2.close()
    store3 = LogFilerStore(path)
    assert os.path.getsize(path) < size_before
    assert Filer(store3).find_entry("/keep/a.txt") is not None
    store3.close()


@pytest.mark.parametrize(
    "store_cls",
    [MemoryFilerStore, SqliteFilerStore, _fresh_log_store, _fresh_lsm_store],
)
def test_rename_overwrites_file_and_frees_chunks(store_cls):
    collected = []
    f = Filer(store_cls(), on_delete_chunks=collected.extend)
    f.touch("/a.bin", "", [chunk("1,aa", 0, 5, 1)])
    f.touch("/b.bin", "", [chunk("2,bb", 0, 7, 1)])
    f.rename("/a.bin", "/b.bin")
    assert collected == ["2,bb"]  # the overwritten destination's chunks
    assert f.find_entry("/a.bin") is None
    assert {c.fid for c in f.find_entry("/b.bin").chunks} == {"1,aa"}

    # overwriting a directory is refused
    f.touch("/d/x.bin", "", [])
    import pytest as _pytest

    with _pytest.raises(IsADirectoryError):
        f.rename("/b.bin", "/d")


@pytest.mark.parametrize(
    "store_cls",
    [MemoryFilerStore, SqliteFilerStore, _fresh_log_store, _fresh_lsm_store],
)
def test_rename_dir_onto_existing_is_refused_before_moving(store_cls):
    """Destination conflicts must be detected BEFORE any child moves, or a
    failed rename leaves half-migrated metadata."""
    f = Filer(store_cls())
    f.touch("/src/one.txt", "", [chunk("1,aa", 0, 5, 1)])
    f.touch("/src/two.txt", "", [chunk("2,bb", 0, 5, 1)])
    f.touch("/dst/other.txt", "", [])

    with pytest.raises(IsADirectoryError):
        f.rename("/src", "/dst")
    # nothing moved: source intact, destination untouched
    assert f.find_entry("/src/one.txt") is not None
    assert f.find_entry("/src/two.txt") is not None
    assert f.find_entry("/dst/one.txt") is None

    # directory onto an existing FILE is a NotADirectoryError, also upfront
    f.touch("/plain.bin", "", [])
    with pytest.raises(NotADirectoryError):
        f.rename("/src", "/plain.bin")
    assert f.find_entry("/src/one.txt") is not None


@pytest.mark.parametrize(
    "store_cls",
    [MemoryFilerStore, SqliteFilerStore, _fresh_log_store, _fresh_lsm_store],
)
def test_create_entry_exclusive(store_cls):
    import pytest as _pytest

    from seaweedfs_tpu.filer.entry import new_directory_entry

    f = Filer(store_cls())
    f.touch("/x.bin", "", [chunk("1,aa", 0, 5, 1)])
    with _pytest.raises(FileExistsError):
        f.create_entry(new_directory_entry("/x.bin"), exclusive=True)
    # the file survived untouched
    assert not f.find_entry("/x.bin").is_directory
