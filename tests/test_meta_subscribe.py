"""Filer metadata subscription: meta log, SubscribeMetadata stream, watch
(ref: weed/util/log_buffer, filer.proto:49-53, command/watch.go)."""

import asyncio

from seaweedfs_tpu.filer.filer import Filer
from seaweedfs_tpu.filer.filer_store import MemoryFilerStore
from seaweedfs_tpu.filer.meta_log import MetaLog


def test_meta_log_append_and_read_since():
    log = MetaLog()
    e1 = log.append("/d", "create", None, {"name": "a"})
    e2 = log.append("/d", "update", {"name": "a"}, {"name": "a"})
    e3 = log.append("/other", "delete", {"name": "b"}, None)
    assert e1.ts_ns < e2.ts_ns < e3.ts_ns  # strictly monotonic

    assert len(log.read_since(0)) == 3
    assert len(log.read_since(e1.ts_ns)) == 2
    assert [e.event_type for e in log.read_since(0, "/d")] == [
        "create",
        "update",
    ]
    assert [e.event_type for e in log.read_since(0, "/other")] == ["delete"]


def test_meta_log_bounded():
    log = MetaLog(capacity=10)
    for i in range(25):
        log.append("/d", "create", None, {"name": str(i)})
    events = log.read_since(0)
    assert len(events) == 10
    assert events[-1].new_entry["name"] == "24"


def test_filer_mutations_feed_meta_log():
    from seaweedfs_tpu.filer.entry import Entry

    filer = Filer(MemoryFilerStore())
    e = Entry(full_path="/dir/f.txt")
    filer.create_entry(e)
    filer.delete_entry("/dir/f.txt")

    events = filer.meta_log.read_since(0, "/dir")
    types = [ev.event_type for ev in events]
    assert "create" in types and "delete" in types
    create = next(ev for ev in events if ev.event_type == "create")
    assert create.directory == "/dir"
    assert create.old_entry is None
    assert create.new_entry["full_path"] == "/dir/f.txt"
    delete = next(ev for ev in events if ev.event_type == "delete")
    assert delete.new_entry is None and delete.old_entry is not None


def test_subscribe_replays_then_follows():
    filer = Filer(MemoryFilerStore())

    async def body():
        from seaweedfs_tpu.filer.entry import Entry

        filer.create_entry(Entry(full_path="/a/1"))
        got = []

        async def consume():
            async for ev in filer.meta_log.subscribe(0, "/a"):
                got.append(ev.event_type)
                if len(got) >= 2:
                    return

        task = asyncio.ensure_future(consume())
        await asyncio.sleep(0.1)
        filer.create_entry(Entry(full_path="/a/2"))
        await asyncio.wait_for(task, timeout=5)
        assert got == ["create", "create"]

    asyncio.run(body())


def test_subscribe_metadata_grpc_stream(tmp_path):
    from test_cluster import Cluster, free_port_pair

    async def body():
        import aiohttp

        from seaweedfs_tpu.pb import grpc_address
        from seaweedfs_tpu.pb.rpc import Stub
        from seaweedfs_tpu.server.filer import FilerServer

        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        fs = FilerServer(master=cluster.master.address, port=free_port_pair())
        await fs.start()
        try:
            await fs.master_client.wait_connected()
            stub = Stub(grpc_address(fs.address), "filer")
            events = []

            async def consume():
                async for msg in stub.server_stream(
                    "SubscribeMetadata",
                    {"client_name": "t", "path_prefix": "/w", "since_ns": 0},
                    timeout=10,
                ):
                    events.append(msg)
                    if len(events) >= 2:
                        return

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.2)
            async with aiohttp.ClientSession() as session:
                async with session.post(
                    f"http://{fs.address}/w/hello.txt", data=b"watch me"
                ) as resp:
                    assert resp.status in (200, 201)
                async with session.delete(
                    f"http://{fs.address}/w/hello.txt"
                ) as resp:
                    assert resp.status in (200, 204)
            await asyncio.wait_for(task, timeout=10)
            kinds = [
                e["event_notification"]["event_type"] for e in events
            ]
            assert kinds[0] == "create" and "delete" in kinds
        finally:
            await fs.stop()
            await cluster.stop()

    asyncio.run(body())


def test_update_and_rename_carry_old_entry():
    from seaweedfs_tpu.filer.entry import Entry, FileChunk

    filer = Filer(MemoryFilerStore())
    filer.create_entry(
        Entry(full_path="/a/x", chunks=[FileChunk(fid="1,ab", offset=0, size=3)])
    )
    filer.create_entry(
        Entry(full_path="/a/x", chunks=[FileChunk(fid="2,cd", offset=0, size=5)])
    )
    update = [e for e in filer.meta_log.read_since(0) if e.event_type == "update"]
    assert update, "overwrite must emit update"
    assert update[0].old_entry["chunks"][0]["fid"] == "1,ab"
    assert update[0].new_entry["chunks"][0]["fid"] == "2,cd"

    filer.rename("/a/x", "/b/y")
    renames = [e for e in filer.meta_log.read_since(0) if e.event_type == "rename"]
    assert renames[-1].old_entry["full_path"] == "/a/x"
    assert renames[-1].new_entry["full_path"] == "/b/y"
    # a subscriber watching the OLD prefix still sees the move
    assert any(
        e.event_type == "rename" for e in filer.meta_log.read_since(0, "/a")
    )


def test_recursive_delete_emits_per_child_events():
    from seaweedfs_tpu.filer.entry import Entry

    filer = Filer(MemoryFilerStore())
    filer.create_entry(Entry(full_path="/top/sub/f1"))
    filer.create_entry(Entry(full_path="/top/sub/f2"))
    mark = filer.meta_log.last_ts_ns
    filer.delete_entry("/top", recursive=True)
    # a subscriber scoped under the deleted tree still sees its deletions
    deep = filer.meta_log.read_since(mark, "/top/sub")
    deleted_paths = {
        (e.old_entry or {}).get("full_path")
        for e in deep
        if e.event_type == "delete"
    }
    assert {"/top/sub/f1", "/top/sub/f2"} <= deleted_paths


def test_directory_rename_emits_per_child_events():
    from seaweedfs_tpu.filer.entry import Entry

    filer = Filer(MemoryFilerStore())
    filer.create_entry(Entry(full_path="/old/d/f1"))
    mark = filer.meta_log.last_ts_ns
    filer.rename("/old", "/new")
    events = filer.meta_log.read_since(mark, "/old/d")
    moved = [
        e
        for e in events
        if e.event_type == "rename"
        and (e.old_entry or {}).get("full_path") == "/old/d/f1"
    ]
    assert moved and moved[0].new_entry["full_path"] == "/new/d/f1"


def test_update_entry_emits_event():
    from seaweedfs_tpu.filer.entry import Entry

    filer = Filer(MemoryFilerStore())
    filer.create_entry(Entry(full_path="/u/f"))
    mark = filer.meta_log.last_ts_ns
    e = filer.find_entry("/u/f")
    e.extended["k"] = "v"
    filer.update_entry(e)
    events = filer.meta_log.read_since(mark, "/u")
    assert [ev.event_type for ev in events] == ["update"]
    assert events[0].new_entry["extended"] == {"k": "v"}


def test_meta_aggregator_two_filers(tmp_path):
    """Peer aggregation (ref weed/filer2/meta_aggregator.go): an entry
    created on filer A (1) streams out of B's aggregate SubscribeMetadata
    and (2) is replayed into B's own store, so the two embedded stores
    converge; B's SubscribeLocalMetadata stays A-silent (no echo loop)."""
    from test_cluster import Cluster, free_port_pair

    async def body():
        from seaweedfs_tpu.pb import grpc_address
        from seaweedfs_tpu.pb.rpc import Stub
        from seaweedfs_tpu.server.filer import FilerServer

        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        fa = FilerServer(master=cluster.master.address, port=free_port_pair())
        await fa.start()
        fb = FilerServer(
            master=cluster.master.address,
            port=free_port_pair(),
            peers=(fa.address,),
            store_path=str(tmp_path / "b.lsm"),
        )
        await fb.start()
        try:
            await fa.master_client.wait_connected()
            events = []

            async def consume():
                stub = Stub(grpc_address(fb.address), "filer")
                async for msg in stub.server_stream(
                    "SubscribeMetadata",
                    {"client_name": "t", "path_prefix": "/agg", "since_ns": 0},
                    timeout=15,
                ):
                    events.append(msg)
                    return

            task = asyncio.ensure_future(consume())
            await asyncio.sleep(0.3)
            from seaweedfs_tpu.filer.entry import Attr, Entry

            fa.filer.create_entry(
                Entry(
                    full_path="/agg/from-a.txt",
                    attr=Attr(mtime=1.0, mode=0o644),
                )
            )
            await asyncio.wait_for(task, timeout=15)
            assert events and events[0]["event_notification"][
                "new_entry"
            ]["full_path"] == "/agg/from-a.txt"

            # replay: B's own store converges on A's entry
            for _ in range(100):
                if fb.filer.find_entry("/agg/from-a.txt") is not None:
                    break
                await asyncio.sleep(0.05)
            assert fb.filer.find_entry("/agg/from-a.txt") is not None

            # and B's LOCAL stream never carries A's event (echo guard)
            local = fb.filer.meta_log.read_since(0, "/agg")
            assert local == []
        finally:
            await fb.stop()
            await fa.stop()
            await cluster.stop()

    asyncio.run(body())
