"""Fault-injection harness + hardened recovery (ISSUE 1).

Three layers of coverage, all fast enough for tier-1 (the chaos smoke is
the every-PR regression gate ISSUE 1 asks for):

- FaultPlan mechanics: determinism for a given seed, nth/probability
  triggers, env-var activation, the dead-plan (post-crash) state.
- Backoff: full-jitter bounds, deadline honoring, retry-until-success.
- Crash recovery: a property test killing the process at 200+ random byte
  offsets (mid .dat record, mid .idx entry, mid fsync) and asserting every
  fully-acked write survives reload and every torn needle is dropped.
- Cluster chaos smoke: a 3-node cluster read workload under a seeded plan
  injecting EIO + resets + latency returns 100% correct bytes.
"""

import asyncio
import os
import random
import time

import pytest

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.util import faults
from seaweedfs_tpu.util.backoff import (
    BackoffPolicy,
    deadline_after,
    remaining,
    retry_async,
)
from seaweedfs_tpu.util.faults import (
    FaultPlan,
    FaultRule,
    InjectedError,
    SimulatedCrash,
)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """Every test starts and ends with injection disabled."""
    faults.clear_plan()
    yield
    faults.clear_plan()


# ---------------------------------------------------------------- plan --


def test_plan_is_deterministic_for_seed():
    def run(seed):
        plan = FaultPlan(seed=seed, rules=[
            FaultRule(op="read_at", target="*", probability=0.3, fault="eio"),
            FaultRule(op="write_at", target="*.dat", nth=5, fault="eio"),
        ])
        events = []
        for i in range(200):
            try:
                ev = plan.match("read_at" if i % 2 else "write_at",
                                f"/v/{i % 3}.dat")
            except BaseException:
                ev = None
            events.append(None if ev is None else (ev.op, ev.kind))
        return events

    assert run(42) == run(42)
    assert run(42) != run(43)  # and the seed actually matters


def test_plan_nth_fires_once():
    plan = FaultPlan(rules=[
        FaultRule(op="sync", target="*", nth=3, fault="eio"),
    ])
    fired = [plan.match("sync", "/x") is not None for _ in range(10)]
    assert fired == [False, False, True] + [False] * 7


def test_plan_times_caps_probability_rule():
    plan = FaultPlan(seed=1, rules=[
        FaultRule(op="op", target="*", probability=1.0, times=2, fault="eio"),
    ])
    fired = [plan.match("op", "t") is not None for _ in range(5)]
    assert fired == [True, True, False, False, False]


def test_plan_dead_after_crash_raises_everywhere():
    plan = FaultPlan(rules=[])
    plan.mark_dead()
    with pytest.raises(SimulatedCrash):
        plan.match("read_at", "/any")


def test_env_var_activation(monkeypatch):
    spec = '{"seed": 9, "rules": [{"op": "read_at", "nth": 1, "fault": "eio"}]}'
    monkeypatch.setenv("SEAWEEDFS_TPU_FAULTS", spec)
    faults._load_env_plan()
    plan = faults.current_plan()
    assert plan is not None and plan.seed == 9
    assert plan.match("read_at", "/x").kind == "eio"
    faults.clear_plan()


def test_plan_roundtrips_through_dict():
    plan = FaultPlan(seed=3, rules=[
        FaultRule(op="write_at", target="*.dat", nth=2, fault="crash", keep=10),
        FaultRule(op="http:GET", probability=0.5, fault="http_error", status=503),
    ])
    plan2 = FaultPlan.from_dict(plan.to_dict())
    assert plan2.to_dict() == plan.to_dict()


# ------------------------------------------------------------- backoff --


def test_backoff_delays_respect_jitter_bounds():
    policy = BackoffPolicy(base=0.1, cap=1.5, multiplier=2.0, attempts=10)
    rng = random.Random(7)
    for attempt in range(10):
        upper = min(1.5, 0.1 * 2.0**attempt)
        for _ in range(50):
            d = policy.delay(attempt, rng)
            assert 0.0 <= d <= upper


def test_retry_async_honors_deadline():
    calls = []

    async def always_fails():
        calls.append(1)
        raise IOError("nope")

    async def body():
        t0 = time.monotonic()
        with pytest.raises(IOError):
            await retry_async(
                always_fails,
                policy=BackoffPolicy(base=0.05, cap=0.05, attempts=1000),
                deadline=deadline_after(0.2),
                rng=random.Random(1),
            )
        return time.monotonic() - t0

    elapsed = asyncio.run(body())
    assert elapsed < 1.0  # nowhere near 1000 attempts' worth
    assert 2 <= len(calls) < 50


def test_retry_async_returns_after_transient_failures():
    attempts = []

    async def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise ConnectionResetError("transient")
        return "ok"

    out = asyncio.run(retry_async(
        flaky,
        policy=BackoffPolicy(base=0.001, cap=0.002, attempts=5),
        rng=random.Random(2),
    ))
    assert out == "ok" and len(attempts) == 3


def test_remaining_converts_deadline_to_timeout():
    assert remaining(None, 30.0) == 30.0
    d = deadline_after(5.0)
    assert 4.0 < remaining(d) <= 5.0
    assert remaining(time.monotonic() - 1.0) == pytest.approx(0.001)


# ------------------------------------------------------- disk backend --


def test_diskfile_eio_write_rolls_back_cleanly(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    v.write_needle(Needle(cookie=1, id=1, data=b"a" * 40))
    faults.install_plan(FaultPlan(rules=[
        FaultRule(op="write_at", target="*.dat", nth=1, fault="torn", keep=9),
    ]))
    with pytest.raises(InjectedError):
        v.write_needle(Needle(cookie=2, id=2, data=b"b" * 40))
    faults.clear_plan()
    # the write path's truncate-rollback ran: the tail is clean and the
    # volume keeps serving
    v.write_needle(Needle(cookie=3, id=3, data=b"c" * 40))
    for nid, byte in ((1, b"a"), (3, b"c")):
        n = Needle(id=nid, cookie=nid)
        v.read_needle(n)
        assert n.data == byte * 40
    v.close()


def test_diskfile_readonly_size_tracks_external_growth(tmp_path):
    from seaweedfs_tpu.storage.backend import DiskFile

    p = str(tmp_path / "grow.dat")
    writer = DiskFile(p)
    writer.write_at(b"x" * 10, 0)
    reader = DiskFile(p, create=False, read_only=True)
    assert reader.size() == 10
    writer.write_at(b"y" * 10, 10)  # concurrent append by another handle
    assert reader.size() == 20  # fstat-backed, not frozen at open time
    assert writer.size() == 20
    writer.close()
    reader.close()


# ------------------------------------------------- crash recovery (PBT) --


def test_crash_recovery_property(tmp_path):
    """Kill the 'process' at an arbitrary byte offset mid-append (in the
    .dat record, the .idx entry, or fsync) and reload: every fully-acked
    write must read back byte-identical, the torn needle must be gone, and
    the volume must come back writable. 200+ seeded kill points."""
    rng = random.Random(0xFA17)
    for it in range(220):
        d = tmp_path / f"it{it}"
        d.mkdir()
        v = Volume(str(d), "", 1)
        acked = {}
        for nid in range(1, rng.randrange(1, 6) + 1):
            data = bytes([rng.randrange(256)]) * rng.randrange(8, 200)
            v.write_needle(Needle(cookie=nid, id=nid, data=data))
            acked[nid] = data
        deleted = None
        if acked and rng.random() < 0.3:
            deleted = rng.choice(list(acked))
            v.delete_needle(Needle(id=deleted, cookie=deleted))
            del acked[deleted]

        victim_data = b"V" * rng.randrange(8, 200)
        where = rng.choice([".dat", ".dat", ".dat", ".idx", "sync"])
        if where == "sync":
            rule = FaultRule(op="sync", target="*", nth=1, fault="crash")
        else:
            # keep is a uniformly random cut point inside the pending
            # append (record for .dat, 16-byte entry for .idx)
            rule = FaultRule(
                op="write_at", target=f"*{where}", nth=1, fault="crash",
                keep=rng.randrange(0, 300),
            )
        faults.install_plan(FaultPlan(seed=it, rules=[rule]))
        try:
            v.write_needle(
                Needle(cookie=99, id=99, data=victim_data),
                sync=(where == "sync"),
            )
            crashed = False
        except SimulatedCrash:
            crashed = True
        except Exception:
            crashed = False  # keep cut past the record: write fine
        finally:
            faults.clear_plan()
        assert crashed, f"iteration {it}: crash fault did not fire"

        v2 = Volume(str(d), "", 1, create=False)
        assert not v2.is_read_only(), f"iteration {it}: stuck read-only"
        for nid, data in acked.items():
            n = Needle(id=nid, cookie=nid)
            assert v2.read_needle(n) == len(data), f"iteration {it}: lost {nid}"
            assert n.data == data, f"iteration {it}: corrupt {nid}"
        if deleted is not None:
            with pytest.raises(Exception):
                v2.read_needle(Needle(id=deleted, cookie=deleted))
        # the victim is either fully recovered or fully gone — never torn
        n = Needle(id=99, cookie=99)
        try:
            v2.read_needle(n)
            assert n.data == victim_data, f"iteration {it}: torn victim"
        except Exception:
            pass
        # and the volume accepts (and persists) new writes
        v2.write_needle(Needle(cookie=7, id=777, data=b"post" * 4))
        n = Needle(id=777, cookie=7)
        v2.read_needle(n)
        assert n.data == b"post" * 4
        v2.close()


def test_key_sorted_idx_reload_is_not_misdiagnosed(tmp_path):
    """`weed-tpu fix` and vacuum rebuild KEY-sorted index files, where the
    last entry is the largest key, not the latest append. The load-time
    frontier check must stay order-independent: no spurious 'torn tail'
    recovery on a healthy volume."""
    from seaweedfs_tpu.storage.backend import DiskFile
    from seaweedfs_tpu.storage.needle_map import MemDb
    from seaweedfs_tpu.storage.super_block import read_super_block
    from seaweedfs_tpu.storage.volume import scan_volume_file
    from seaweedfs_tpu.types import to_offset_units

    v = Volume(str(tmp_path), "", 3)
    # dat order k1, k5, k1': in a key-sorted idx the LAST entry (k5) ends
    # mid-file — a naive last-entry frontier would cry torn tail here
    v.write_needle(Needle(cookie=1, id=1, data=b"a" * 50))
    v.write_needle(Needle(cookie=5, id=5, data=b"e" * 50))
    v.write_needle(Needle(cookie=1, id=1, data=b"A" * 70))
    v.close()

    base = str(tmp_path / "3")
    dat = DiskFile(base + ".dat", create=False, read_only=True)
    sb = read_super_block(dat)
    nm = MemDb()

    def visit(n, offset, body):
        if n.size > 0:
            nm.set(n.id, to_offset_units(offset), n.size)
        else:
            nm.delete(n.id)

    scan_volume_file(dat, sb, visit, read_body=False)
    nm.save_to_idx(base + ".idx")  # key-sorted, like cli.py _fix
    dat.close()

    v2 = Volume(str(tmp_path), "", 3, create=False)
    assert v2.recovery_stats is None  # no spurious recovery
    assert not v2.is_read_only()
    n = Needle(id=1, cookie=1)
    v2.read_needle(n)
    assert n.data == b"A" * 70
    v2.close()


def test_injected_hang_respects_call_timeout():
    """An injected RPC hang must surface through the caller's timeout,
    not a hardcoded 30s — the deadline propagation is the contract."""
    plan = FaultPlan(rules=[FaultRule(op="rpc:Slow", fault="hang")])

    async def body():
        t0 = time.monotonic()
        with pytest.raises(TimeoutError):
            await faults.async_fault(plan, "rpc:Slow", "h:1", timeout=0.05)
        return time.monotonic() - t0

    assert asyncio.run(body()) < 1.0


def test_crash_fault_fires_on_non_write_seams():
    """A crash rule matching read_at/truncate must actually kill the plan,
    never be a counted no-op."""
    from seaweedfs_tpu.storage.backend import DiskFile

    plan = FaultPlan(rules=[FaultRule(op="read_at", nth=1, fault="crash")])
    faults.install_plan(plan)
    import tempfile

    with tempfile.NamedTemporaryFile() as f:
        df = DiskFile(f.name)
        df.write_at(b"x" * 8, 0)
        with pytest.raises(SimulatedCrash):
            df.read_at(4, 0)
        assert plan.dead
        with pytest.raises(SimulatedCrash):
            df.write_at(b"y", 0)  # everything after the crash is dead
        df.close()


def test_bitrot_still_goes_readonly_not_truncated(tmp_path):
    """In-place corruption of an ACKED record is not a crash artifact:
    recovery must refuse to truncate it and mark the volume read-only."""
    v = Volume(str(tmp_path), "", 5)
    v.write_needle(Needle(cookie=1, id=1, data=b"a" * 64))
    v.write_needle(Needle(cookie=2, id=2, data=b"b" * 64))
    v.close()
    dat = str(tmp_path / "5.dat")
    size = os.path.getsize(dat)
    with open(dat, "r+b") as f:
        f.seek(size - 30)
        f.write(b"\xff" * 4)
    v2 = Volume(str(tmp_path), "", 5, create=False)
    assert v2.is_read_only()
    assert os.path.getsize(dat) == size  # evidence intact
    v2.close()


# ------------------------------------------------------- cluster chaos --


def test_cluster_chaos_read_workload(tmp_path):
    """The every-PR chaos smoke: write 18 blobs into a 3-node cluster,
    then read them all back (twice) under a seeded plan injecting EIO on
    10% of disk reads, resets + latency on the client HTTP path and
    latency on 10% of RPCs. Reads retry with backoff — degraded service
    is allowed, wrong bytes or data loss are not."""
    from test_cluster import Cluster, assign_retry

    from seaweedfs_tpu.util.fasthttp import FastHTTPClient

    async def body():
        import aiohttp

        cluster = Cluster(tmp_path)
        await cluster.start()
        client = FastHTTPClient()
        try:
            async with aiohttp.ClientSession() as session:
                from seaweedfs_tpu.client.operation import upload_data

                payloads = {}
                for i in range(18):
                    ar = await assign_retry(cluster.master.address)
                    data = random.Random(i).randbytes(500 + 37 * i)
                    await upload_data(
                        session, ar.url, ar.fid, data, filename=f"c{i}.bin"
                    )
                    payloads[(ar.url, ar.fid)] = data

            plan = FaultPlan(seed=0xC405, rules=[
                FaultRule(op="read_at", target="*.dat",
                          probability=0.10, fault="eio"),
                FaultRule(op="http:GET", target="*",
                          probability=0.10, fault="reset"),
                FaultRule(op="http:GET", target="*", nth=3,
                          fault="reset"),  # at least one guaranteed fault
                FaultRule(op="http:GET", target="*",
                          probability=0.10, fault="latency", delay=0.02),
                FaultRule(op="rpc:*", target="*",
                          probability=0.10, fault="latency", delay=0.02),
            ])
            faults.install_plan(plan)

            async def read_with_retry(url, fid):
                async def one():
                    status, body = await client.request("GET", url, f"/{fid}")
                    if status != 200:
                        raise IOError(f"status {status}")
                    return body

                return await retry_async(
                    one,
                    policy=BackoffPolicy(base=0.01, cap=0.1, attempts=8),
                    deadline=deadline_after(10.0),
                    rng=random.Random(hash(fid) & 0xFFFF),
                )

            for _pass in range(2):
                for (url, fid), data in payloads.items():
                    got = await read_with_retry(url, fid)
                    assert got == data, f"wrong bytes for {fid} under chaos"
            assert plan.fired() > 0, "chaos plan never fired"
            faults.clear_plan()
        finally:
            faults.clear_plan()
            await client.close()
            await cluster.stop()

    asyncio.run(body())


# ---------------- process-level fault schedules (ISSUE 16) ----------------


def test_process_fault_schedule_deterministic():
    """The chaos soak's reproducibility claim: same (seed, targets,
    window) regenerates the IDENTICAL schedule, different seeds don't."""
    from seaweedfs_tpu.util.faults import (
        process_fault_schedule,
        process_schedule_to_dicts,
    )

    targets = ["volume-0", "volume-1", "volume-2"]
    a = process_fault_schedule(7, targets, 60.0, count=6)
    b = process_fault_schedule(7, targets, 60.0, count=6)
    assert process_schedule_to_dicts(a) == process_schedule_to_dicts(b)
    c = process_fault_schedule(8, targets, 60.0, count=6)
    assert process_schedule_to_dicts(a) != process_schedule_to_dicts(c)


def test_process_fault_schedule_kinds_cycle():
    """Every requested kind appears before any repeats — the guarantee
    the soak leans on for '>= 1 SIGKILL with recovery'."""
    from seaweedfs_tpu.util.faults import process_fault_schedule

    sched = process_fault_schedule(
        3, ["volume-0"], 30.0, count=3, kinds=("kill", "pause", "restart")
    )
    assert sorted(f.kind for f in sched) == ["kill", "pause", "restart"]
    only_restart = process_fault_schedule(
        3, ["volume-0"], 30.0, count=2, kinds=("restart",)
    )
    assert {f.kind for f in only_restart} == {"restart"}


def test_process_fault_schedule_shape():
    from seaweedfs_tpu.util.faults import (
        PROCESS_FAULT_KINDS,
        process_fault_schedule,
    )

    sched = process_fault_schedule(
        11, ["volume-0", "filer-1"], 45.0, count=8, start_s=5.0
    )
    assert len(sched) == 8
    assert sched == sorted(sched, key=lambda f: (f.at_s, f.target, f.kind))
    for f in sched:
        assert 5.0 <= f.at_s <= 50.0
        assert f.kind in PROCESS_FAULT_KINDS
        assert f.target in ("volume-0", "filer-1")
        if f.kind == "pause":
            assert f.duration_s > 0


def test_process_fault_serialization_round_trip():
    from seaweedfs_tpu.util.faults import (
        process_fault_schedule,
        process_schedule_from_dicts,
        process_schedule_to_dicts,
    )

    sched = process_fault_schedule(21, ["volume-0", "volume-1"], 40.0,
                                   count=5)
    dicts = process_schedule_to_dicts(sched)
    back = process_schedule_from_dicts(dicts)
    assert process_schedule_to_dicts(back) == dicts
    # json-clean: the soak publishes the schedule in its result dict
    import json as _json

    assert _json.loads(_json.dumps(dicts)) == dicts
