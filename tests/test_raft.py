"""Multi-master raft-lite: election, leader-kill failover, assign
continuity with a monotonic max-volume-id
(ref weed/server/raft_server.go, weed/topology/topology.go:115-122).
"""

import asyncio

import aiohttp
import pytest

from seaweedfs_tpu.pb.rpc import close_all_channels
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer

from test_cluster import free_port_pair


async def _wait_for(predicate, timeout=15.0, interval=0.1, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


class MultiMasterCluster:
    def __init__(self, tmp_path, n_masters=3, n_volume_servers=2):
        self.tmp_path = tmp_path
        self.n_masters = n_masters
        self.n_vs = n_volume_servers
        self.masters: list[MasterServer] = []
        self.volume_servers: list[VolumeServer] = []

    async def start(self):
        ports = [free_port_pair() for _ in range(self.n_masters)]
        addrs = [f"127.0.0.1:{p}" for p in ports]
        for p in ports:
            m = MasterServer(port=p, pulse_seconds=0.2, peers=addrs)
            await m.start()
            self.masters.append(m)
        await _wait_for(
            lambda: self.leader() is not None, msg="leader election"
        )
        for i in range(self.n_vs):
            d = self.tmp_path / f"vol{i}"
            d.mkdir(exist_ok=True)
            vs = VolumeServer(
                master=addrs,
                directories=[str(d)],
                port=free_port_pair(),
                pulse_seconds=0.2,
                max_volume_counts=[20],
            )
            await vs.start()
            self.volume_servers.append(vs)
        await _wait_for(
            lambda: self.leader() is not None
            and len(self.leader().topo.data_nodes()) == self.n_vs,
            msg="volume servers registered with leader",
        )

    def leader(self):
        leaders = [m for m in self.masters if m.raft.is_leader]
        return leaders[0] if len(leaders) == 1 else None

    def followers(self):
        return [m for m in self.masters if not m.raft.is_leader]

    async def stop(self):
        for vs in self.volume_servers:
            await vs.stop()
        for m in self.masters:
            await m.stop()
        await close_all_channels()


def test_election_failover_and_monotonic_assign(tmp_path):
    async def body():
        cluster = MultiMasterCluster(tmp_path)
        try:
            await cluster.start()
            leader = cluster.leader()
            assert leader is not None

            # assign via a FOLLOWER's HTTP endpoint: must proxy to leader
            follower = cluster.followers()[0]
            async with aiohttp.ClientSession() as http:
                async with http.get(
                    f"http://{follower.address}/dir/assign"
                ) as resp:
                    a1 = await resp.json()
            assert "fid" in a1, a1
            vid_before = leader.topo.max_volume_id
            assert vid_before >= 1

            # kill the leader
            dead = leader.address
            cluster.masters.remove(leader)
            await leader.stop()

            # a new leader is elected among the remaining masters
            await _wait_for(
                lambda: cluster.leader() is not None, msg="re-election"
            )
            new_leader = cluster.leader()
            assert new_leader.address != dead
            # max-volume-id agreement survived the failover
            assert new_leader.topo.max_volume_id >= vid_before

            # volume servers re-register with the new leader
            await _wait_for(
                lambda: len(cluster.leader().topo.data_nodes())
                == cluster.n_vs,
                msg="volume servers re-registered",
            )

            # assign keeps working and never regresses volume ids
            async with aiohttp.ClientSession() as http:
                for m in cluster.masters:
                    async with http.get(
                        f"http://{m.address}/dir/assign"
                    ) as resp:
                        a2 = await resp.json()
                    assert "fid" in a2, a2
                    new_vid = int(a2["fid"].split(",")[0])
                    assert new_vid >= 1
            assert cluster.leader().topo.max_volume_id >= vid_before
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_follower_redirects_streams(tmp_path):
    """A follower master must not accept heartbeats or KeepConnected
    clients: it redirects both to the leader."""

    async def body():
        cluster = MultiMasterCluster(tmp_path, n_volume_servers=1)
        try:
            await cluster.start()
            # only the leader's topology has the data node
            for m in cluster.followers():
                assert len(m.topo.data_nodes()) == 0
            assert len(cluster.leader().topo.data_nodes()) == 1

            # cluster status reflects raft state
            async with aiohttp.ClientSession() as http:
                f = cluster.followers()[0]
                async with http.get(
                    f"http://{f.address}/cluster/status"
                ) as resp:
                    st = await resp.json()
            assert st["IsLeader"] is False
            assert st["Leader"] == cluster.leader().address
        finally:
            await cluster.stop()

    asyncio.run(body())
