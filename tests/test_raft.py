"""Multi-master raft-lite: election, leader-kill failover, assign
continuity with a monotonic max-volume-id
(ref weed/server/raft_server.go, weed/topology/topology.go:115-122).
"""

import asyncio

import aiohttp
import pytest

from seaweedfs_tpu.pb.rpc import close_all_channels
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer

from test_cluster import free_port_pair


async def _wait_for(predicate, timeout=15.0, interval=0.1, msg="condition"):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return
        await asyncio.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


class MultiMasterCluster:
    def __init__(self, tmp_path, n_masters=3, n_volume_servers=2):
        self.tmp_path = tmp_path
        self.n_masters = n_masters
        self.n_vs = n_volume_servers
        self.masters: list[MasterServer] = []
        self.volume_servers: list[VolumeServer] = []

    async def start(self):
        ports = [free_port_pair() for _ in range(self.n_masters)]
        addrs = [f"127.0.0.1:{p}" for p in ports]
        for p in ports:
            m = MasterServer(port=p, pulse_seconds=0.2, peers=addrs)
            await m.start()
            self.masters.append(m)
        await _wait_for(
            lambda: self.leader() is not None, msg="leader election"
        )
        for i in range(self.n_vs):
            d = self.tmp_path / f"vol{i}"
            d.mkdir(exist_ok=True)
            vs = VolumeServer(
                master=addrs,
                directories=[str(d)],
                port=free_port_pair(),
                pulse_seconds=0.2,
                max_volume_counts=[20],
            )
            await vs.start()
            self.volume_servers.append(vs)
        await _wait_for(
            lambda: self.leader() is not None
            and len(self.leader().topo.data_nodes()) == self.n_vs,
            msg="volume servers registered with leader",
        )

    def leader(self):
        leaders = [m for m in self.masters if m.raft.is_leader]
        return leaders[0] if len(leaders) == 1 else None

    def followers(self):
        return [m for m in self.masters if not m.raft.is_leader]

    async def stop(self):
        for vs in self.volume_servers:
            await vs.stop()
        for m in self.masters:
            await m.stop()
        await close_all_channels()


def test_election_failover_and_monotonic_assign(tmp_path):
    async def body():
        cluster = MultiMasterCluster(tmp_path)
        try:
            await cluster.start()
            leader = cluster.leader()
            assert leader is not None

            # assign via a FOLLOWER's HTTP endpoint: must proxy to leader
            follower = cluster.followers()[0]
            async with aiohttp.ClientSession() as http:
                async with http.get(
                    f"http://{follower.address}/dir/assign"
                ) as resp:
                    a1 = await resp.json()
            assert "fid" in a1, a1
            vid_before = leader.topo.max_volume_id
            assert vid_before >= 1

            # kill the leader
            dead = leader.address
            cluster.masters.remove(leader)
            await leader.stop()

            # a new leader is elected among the remaining masters
            await _wait_for(
                lambda: cluster.leader() is not None, msg="re-election"
            )
            new_leader = cluster.leader()
            assert new_leader.address != dead
            # max-volume-id agreement survived the failover
            assert new_leader.topo.max_volume_id >= vid_before

            # volume servers re-register with the new leader
            await _wait_for(
                lambda: len(cluster.leader().topo.data_nodes())
                == cluster.n_vs,
                msg="volume servers re-registered",
            )

            # assign keeps working and never regresses volume ids
            async with aiohttp.ClientSession() as http:
                for m in cluster.masters:
                    async with http.get(
                        f"http://{m.address}/dir/assign"
                    ) as resp:
                        a2 = await resp.json()
                    assert "fid" in a2, a2
                    new_vid = int(a2["fid"].split(",")[0])
                    assert new_vid >= 1
            assert cluster.leader().topo.max_volume_id >= vid_before
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_follower_redirects_streams(tmp_path):
    """A follower master must not accept heartbeats or KeepConnected
    clients: it redirects both to the leader."""

    async def body():
        cluster = MultiMasterCluster(tmp_path, n_volume_servers=1)
        try:
            await cluster.start()
            # only the leader's topology has the data node
            for m in cluster.followers():
                assert len(m.topo.data_nodes()) == 0
            assert len(cluster.leader().topo.data_nodes()) == 1

            # cluster status reflects raft state
            async with aiohttp.ClientSession() as http:
                f = cluster.followers()[0]
                async with http.get(
                    f"http://{f.address}/cluster/status"
                ) as resp:
                    st = await resp.json()
            assert st["IsLeader"] is False
            assert st["Leader"] == cluster.leader().address
        finally:
            await cluster.stop()

    asyncio.run(body())


def _partition(master):
    """Isolate a master: its raft can neither reach peers nor be reached
    (instance-attr shadowing intercepts both directions). Returns a heal()."""
    raft = master.raft

    async def broadcast_dropped(method, req):
        return []  # nobody reachable; not a step-down

    async def vote_dropped(req):
        raise ConnectionError("partitioned")

    async def append_dropped(req):
        raise ConnectionError("partitioned")

    orig = (raft._broadcast, raft.handle_request_vote, raft.handle_append_entries)
    raft._broadcast = broadcast_dropped
    raft.handle_request_vote = vote_dropped
    raft.handle_append_entries = append_dropped

    def heal():
        raft._broadcast, raft.handle_request_vote, raft.handle_append_entries = orig

    return heal


def test_partition_leader_steps_down_and_heals(tmp_path):
    """Classic partition: the isolated leader loses its quorum lease and
    stops acting as leader (no split brain); the majority elects a new
    leader at a higher term; after healing the old leader rejoins as a
    follower of the new one."""

    async def body():
        cluster = MultiMasterCluster(tmp_path, n_volume_servers=1)
        try:
            await cluster.start()
            old = cluster.leader()
            old_term = old.raft.term
            heal = _partition(old)

            # majority side elects a new leader at a higher term
            await _wait_for(
                lambda: any(
                    m.raft.is_leader and m is not old for m in cluster.masters
                ),
                msg="majority re-election",
            )
            # the partitioned leader loses its lease and steps down: at no
            # point after that do two masters answer assigns as leader
            await _wait_for(
                lambda: not old.raft.is_leader, msg="old leader steps down"
            )
            new = next(
                m for m in cluster.masters if m.raft.is_leader and m is not old
            )
            assert new.raft.term > old_term

            # volume servers re-register with the new leader, then assigns
            # flow through it
            await _wait_for(
                lambda: len(new.topo.data_nodes()) == cluster.n_vs,
                msg="volume servers re-registered with new leader",
            )
            async with aiohttp.ClientSession() as http:
                async with http.get(
                    f"http://{new.address}/dir/assign"
                ) as resp:
                    assert "fid" in await resp.json()

            heal()
            # the healed node converges: same term, follows the new leader
            await _wait_for(
                lambda: old.raft.term == new.raft.term
                and not old.raft.is_leader
                and old.raft.leader_address == new.address,
                msg="healed node follows new leader",
            )
            assert sum(1 for m in cluster.masters if m.raft.is_leader) == 1
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_leader_flapping_converges(tmp_path):
    """Repeatedly partition whoever leads; every round the survivors elect
    exactly one replacement, assigns keep working, and the max volume id
    never regresses."""

    async def body():
        cluster = MultiMasterCluster(tmp_path, n_volume_servers=1)
        try:
            await cluster.start()
            max_vid_seen = 0
            for _round in range(3):
                leader = cluster.leader()
                heal = _partition(leader)
                await _wait_for(
                    lambda: any(
                        m.raft.is_leader and not m.raft is leader.raft
                        for m in cluster.masters
                    )
                    and not leader.raft.is_leader,
                    msg=f"round {_round} re-election",
                )
                heal()
                await _wait_for(
                    lambda: cluster.leader() is not None
                    and len(
                        {m.raft.term for m in cluster.masters}
                    ) == 1,
                    msg=f"round {_round} convergence",
                )
                new_leader = cluster.leader()
                await _wait_for(
                    lambda: len(cluster.leader().topo.data_nodes())
                    == cluster.n_vs,
                    msg=f"round {_round} volume servers re-registered",
                )
                async with aiohttp.ClientSession() as http:
                    async with http.get(
                        f"http://{new_leader.address}/dir/assign"
                    ) as resp:
                        a = await resp.json()
                assert "fid" in a, a
                vid = int(a["fid"].split(",")[0])
                assert vid >= 1
                assert new_leader.topo.max_volume_id >= max_vid_seen
                max_vid_seen = new_leader.topo.max_volume_id
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_partition_fault_kind_drops_both_directions():
    """The `partition` fault kind (ISSUE 9 satellite): one windowed rule
    cuts traffic in BOTH orientations between two addresses, anonymous
    callers only match the wildcard side, and the window heals it."""
    from seaweedfs_tpu.util import faults

    plan = faults.FaultPlan(
        seed=1, rules=[faults.partition("a:1", "b:2")]
    )

    async def body():
        # a -> b: source tagged via calling_from
        with faults.calling_from("a:1"):
            with pytest.raises(ConnectionError):
                await faults.async_fault(plan, "rpc:Ping", "b:2")
        # b -> a: the SAME rule, opposite orientation
        with faults.calling_from("b:2"):
            with pytest.raises(ConnectionError):
                await faults.async_fault(plan, "rpc:Ping", "a:1")
        # c -> b: not part of the cut
        with faults.calling_from("c:3"):
            assert await faults.async_fault(plan, "rpc:Ping", "b:2") is None
        # anonymous -> b: only a wildcard peer side may match a None
        # source, and this rule's peer is concrete
        assert await faults.async_fault(plan, "rpc:Ping", "b:2") is None
        # wildcard isolation: partition("a:1") cuts a:1 off from everyone,
        # anonymous callers included
        plan2 = faults.FaultPlan(seed=2, rules=[faults.partition("a:1")])
        with pytest.raises(ConnectionError):
            await faults.async_fault(plan2, "rpc:Ping", "a:1")
        with faults.calling_from("a:1"):
            with pytest.raises(ConnectionError):
                await faults.async_fault(plan2, "rpc:Ping", "anyone:9")

        # windowed like brownout: outside [start, start+duration) the
        # rule neither fires nor counts
        plan3 = faults.FaultPlan(
            seed=3,
            rules=[faults.partition("a:1", start=10.0, duration=5.0)],
        )
        assert await faults.async_fault(plan3, "rpc:Ping", "a:1") is None
        assert plan3.fired() == 0

    asyncio.run(body())
    assert plan.fired() == 2  # a->b and b->a; nothing else matched


def test_injected_partition_deposes_leader_and_writes_resume(tmp_path):
    """The raft cluster under the REAL `partition` fault kind (not
    method monkeypatching): the leader is cut off at the RPC seam in
    both directions, the majority elects a successor, writes (assigns)
    resume through it, and clearing the plan heals the cluster."""
    from seaweedfs_tpu.util import faults

    async def body():
        cluster = MultiMasterCluster(tmp_path, n_volume_servers=1)
        try:
            await cluster.start()
            old = cluster.leader()
            from seaweedfs_tpu.pb import grpc_address

            # two rules cover both orientations across the two address
            # spaces in play: inbound anything -> the leader's gRPC
            # listener, and outbound anything FROM the leader (raft
            # broadcasts tag their source with the master address)
            plan = faults.FaultPlan(
                seed=0xBEEF,
                rules=[
                    faults.partition(grpc_address(old.address)),
                    faults.partition("*", old.address),
                ],
            )
            faults.install_plan(plan)
            try:
                await _wait_for(
                    lambda: any(
                        m.raft.is_leader and m is not old
                        for m in cluster.masters
                    )
                    and not old.raft.is_leader,
                    msg="majority re-election under injected partition",
                )
                new = next(
                    m
                    for m in cluster.masters
                    if m.raft.is_leader and m is not old
                )
                assert plan.fired("rpc:*") > 0
                # writes resume through the new leader once the volume
                # server re-registers
                await _wait_for(
                    lambda: len(new.topo.data_nodes()) == cluster.n_vs,
                    msg="volume server re-registered with new leader",
                )
                async with aiohttp.ClientSession() as http:
                    async with http.get(
                        f"http://{new.address}/dir/assign"
                    ) as resp:
                        assert "fid" in await resp.json()
            finally:
                faults.clear_plan()

            # heal: the old leader converges onto the new term
            new = cluster.leader()
            await _wait_for(
                lambda: old.raft.term == new.raft.term
                and not old.raft.is_leader,
                msg="healed node follows new leader",
            )
            assert sum(1 for m in cluster.masters if m.raft.is_leader) == 1
        finally:
            await cluster.stop()

    asyncio.run(body())


def test_keep_connected_redial_rate_bounded_when_budget_dry(tmp_path):
    """During a cluster-wide outage the master redial loop must not
    tight-loop: with the shared retry budget drained, the delay pins at
    the policy cap, so a ~1.2s outage window sees a bounded number of
    keep-connected attempts instead of a storm."""
    from seaweedfs_tpu.client import MasterClient
    from seaweedfs_tpu.util.backoff import (
        RetryBudget,
        configure_retry_budget,
    )
    from seaweedfs_tpu.util.metrics import RETRY_COUNTER

    async def body():
        budget = RetryBudget(ratio=0.1, max_tokens=10.0)
        for _ in range(6):
            budget.on_failure()  # below half: retries suppressed
        configure_retry_budget(budget)
        key = (("op", "keep_connected"),)
        before = RETRY_COUNTER._values.get(key, 0)
        # nothing listens on this address: every connect attempt fails
        mc = MasterClient("t-redial", [f"127.0.0.1:{free_port_pair()}"])
        await mc.start()
        try:
            await asyncio.sleep(1.2)
        finally:
            await mc.stop()
        attempts = RETRY_COUNTER._values.get(key, 0) - before
        # first failure backs off at base jitter, every subsequent one at
        # the 5s cap: a 1.2s window fits at most ~3 attempts. 20+ means
        # the budget was ignored and the loop is hammering.
        assert attempts <= 4, f"unbounded redial: {attempts} in 1.2s"

    asyncio.run(body())


def test_raft_state_persistence(tmp_path):
    """A restarted node reloads (term, voted_for, max_volume_id): it cannot
    grant a second vote in the same term, and the committed id survives."""
    from seaweedfs_tpu.server.raft import RaftLite

    async def body():
        state = str(tmp_path / "raft.json")
        seen_vid = {"v": 0}
        r1 = RaftLite(
            "127.0.0.1:1",
            peers=["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"],
            get_max_volume_id=lambda: seen_vid["v"],
            adjust_max_volume_id=lambda v: seen_vid.update(
                v=max(seen_vid["v"], v)
            ),
            state_file=state,
        )
        resp = await r1.handle_request_vote(
            {"term": 7, "candidate": "127.0.0.1:2", "max_volume_id": 41}
        )
        assert resp["granted"] and r1.term == 7
        assert seen_vid["v"] == 41

        # crash + restart: state reloads from disk
        seen_vid2 = {"v": 0}
        r2 = RaftLite(
            "127.0.0.1:1",
            peers=["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"],
            get_max_volume_id=lambda: seen_vid2["v"],
            adjust_max_volume_id=lambda v: seen_vid2.update(
                v=max(seen_vid2["v"], v)
            ),
            state_file=state,
        )
        assert r2.term == 7
        assert r2.voted_for == "127.0.0.1:2"
        assert seen_vid2["v"] == 41
        # a DIFFERENT candidate in the same term is refused (no double vote)
        resp = await r2.handle_request_vote(
            {"term": 7, "candidate": "127.0.0.1:3", "max_volume_id": 0}
        )
        assert not resp["granted"]
        # the original candidate may retry and is re-granted
        resp = await r2.handle_request_vote(
            {"term": 7, "candidate": "127.0.0.1:2", "max_volume_id": 0}
        )
        assert resp["granted"]

    asyncio.run(body())
