"""BatchLookupGate: micro-batched read serving through a live cluster
(north-star #2 e2e; ref read path: volume_server_handlers_read.go:28-39)."""

import asyncio
import random
import socket

import aiohttp
import pytest

from seaweedfs_tpu.client import assign
from seaweedfs_tpu.client.operation import read_url, upload_data
from seaweedfs_tpu.pb.rpc import close_all_channels
from seaweedfs_tpu.server.master import MasterServer
from seaweedfs_tpu.server.volume import VolumeServer


def _free_port() -> int:
    for p in range(21000, 22000):
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", p))
            with socket.socket() as s:
                s.bind(("127.0.0.1", p + 10000))
            return p
        except OSError:
            continue
    raise RuntimeError("no free port pair")


@pytest.mark.parametrize("mode", ["host", "auto"])
def test_batched_reads_serve_correct_bytes(tmp_path, mode):
    async def body():
        ms = MasterServer(port=_free_port(), pulse_seconds=0.2)
        await ms.start()
        vs = VolumeServer(
            master=ms.address,
            directories=[str(tmp_path)],
            port=_free_port(),
            pulse_seconds=0.2,
            max_volume_counts=[10],
            batch_lookup=mode,
        )
        await vs.start()
        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)
            assert vs.lookup_gate is not None

            payloads = {}
            async with aiohttp.ClientSession() as session:
                for i in range(40):
                    ar = await assign(ms.address)
                    data = random.randbytes(500 + i)
                    await upload_data(session, ar.url, ar.fid, data)
                    payloads[ar.fid] = (ar.url, data)

                # concurrent reads land in shared micro-batches
                async def read_one(fid, url, want):
                    got = await read_url(session, f"http://{url}/{fid}")
                    assert got == want, fid

                await asyncio.gather(
                    *(
                        read_one(fid, url, data)
                        for fid, (url, data) in payloads.items()
                    )
                )
                assert vs.lookup_gate.stats["probes"] >= len(payloads)
                assert vs.lookup_gate.stats["largest_batch"] > 1
                assert (
                    vs.lookup_gate.stats["batches"]
                    < vs.lookup_gate.stats["probes"]
                )

                # absent needle and wrong cookie both 404 through the gate
                some_fid, (url, _) = next(iter(payloads.items()))
                vid = some_fid.split(",")[0]
                async with session.get(
                    f"http://{url}/{vid},ffffffffffffffff"
                ) as resp:
                    assert resp.status in (400, 404)
                wrong_cookie = some_fid[:-8] + (
                    "00000001"
                    if some_fid[-8:] != "00000001"
                    else "00000002"
                )
                async with session.get(
                    f"http://{url}/{wrong_cookie}"
                ) as resp:
                    assert resp.status == 404

                # delete, then the gate must report it gone
                async with session.delete(
                    f"http://{url}/{some_fid}"
                ) as resp:
                    assert resp.status in (200, 202)
                async with session.get(f"http://{url}/{some_fid}") as resp:
                    assert resp.status == 404
        finally:
            await vs.stop()
            await ms.stop()
            await close_all_channels()

    asyncio.run(body())


def test_gate_stale_offset_falls_back_to_locked_read(tmp_path):
    """If a vacuum commit rewrites the volume between the gate's batched
    probe and the pread, the handler re-resolves through the locked
    per-request path instead of serving garbage or a spurious 404."""

    async def body():
        ms = MasterServer(port=_free_port(), pulse_seconds=0.2)
        await ms.start()
        vs = VolumeServer(
            master=ms.address,
            directories=[str(tmp_path)],
            port=_free_port(),
            pulse_seconds=0.2,
            max_volume_counts=[10],
            batch_lookup="host",
        )
        await vs.start()
        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)
            async with aiohttp.ClientSession() as session:
                ar = await assign(ms.address)
                data = random.randbytes(1234)
                await upload_data(session, ar.url, ar.fid, data)
                vid = int(ar.fid.split(",")[0])
                v = vs.store.find_volume(vid)

                # poison the offset-based read ONCE, as a post-compaction
                # stale offset would: the handler must retry via the
                # authoritative locked path
                real = v.read_needle_at
                calls = {"n": 0}

                def poisoned(offset_units, size):
                    calls["n"] += 1
                    if calls["n"] == 1:
                        raise IOError("stale offset after vacuum commit")
                    return real(offset_units, size)

                v.read_needle_at = poisoned
                got = await read_url(session, f"http://{ar.url}/{ar.fid}")
                assert got == data
                assert calls["n"] >= 1
        finally:
            await vs.stop()
            await ms.stop()
            await close_all_channels()

    asyncio.run(body())


def test_gate_close_cancels_waiters(tmp_path):
    from seaweedfs_tpu.server.lookup_gate import BatchLookupGate

    class _Store:
        def find_volume(self, vid):
            return None

    async def body():
        gate = BatchLookupGate(_Store(), window_ms=1000)
        task = asyncio.ensure_future(gate.lookup(1, 42))
        await asyncio.sleep(0.01)
        gate.close()
        with pytest.raises(LookupError):
            await task

    asyncio.run(body())
