"""Bulk index-lookup serving path: Volume.bulk_lookup, EcVolume.bulk_locate,
and the BulkLookup / BatchRead volume-server RPCs.

The device path runs the batched binary search of ops/index_kernel.py over a
cached snapshot; these tests assert parity with the per-key map path
(ref: weed/storage/needle_map/compact_map.go:145-172 — the search this
replaces) plus cache invalidation on writes/deletes.
"""

import asyncio
import random

import aiohttp
import numpy as np
import pytest

from seaweedfs_tpu.storage.erasure_coding import (
    write_ec_files,
    write_sorted_file_from_idx,
)
from seaweedfs_tpu.storage.erasure_coding.ec_volume import EcVolume
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

LARGE_BLOCK = 1 << 14
SMALL_BLOCK = 1 << 10


def new_needle(nid: int, size: int = 100, cookie: int = 0x42) -> Needle:
    n = Needle(cookie=cookie, id=nid)
    n.data = random.randbytes(size)
    return n


def _fill_volume(v: Volume, n_keys: int = 200) -> list[int]:
    keys = sorted(random.sample(range(1, 1 << 40), n_keys))
    for k in keys:
        v.write_needle(new_needle(k, size=random.randint(1, 300)))
    return keys


@pytest.mark.parametrize("use_device", [True, False])
def test_volume_bulk_lookup_matches_per_key(tmp_path, use_device):
    random.seed(17)
    v = Volume(str(tmp_path), "", 1)
    keys = _fill_volume(v)
    deleted = keys[::5]
    for k in deleted:
        v.delete_needle(Needle(id=k, cookie=0x42))

    probes = np.array(
        keys + [7, 9, (1 << 41) + 3], dtype=np.uint64
    )  # all keys + misses
    offsets, sizes, found = v.bulk_lookup(probes, use_device=use_device)
    for i, k in enumerate(keys):
        nv = v.nm.get(k)
        if k in deleted:
            assert not found[i], k
        else:
            assert found[i], k
            assert offsets[i] == nv.offset_units
            assert sizes[i] == nv.size
    assert not found[-3:].any()
    v.close()


def test_volume_bulk_lookup_cache_invalidation(tmp_path):
    random.seed(5)
    v = Volume(str(tmp_path), "", 2)
    v.write_needle(new_needle(10))
    probes = np.array([10, 11], dtype=np.uint64)
    _, _, found = v.bulk_lookup(probes, use_device=True)
    assert found[0] and not found[1]

    # a write after the snapshot must be visible to the next bulk probe
    v.write_needle(new_needle(11))
    _, _, found = v.bulk_lookup(probes, use_device=True)
    assert found.all()

    # ... and so must a delete
    v.delete_needle(Needle(id=10, cookie=0x42))
    _, _, found = v.bulk_lookup(probes, use_device=True)
    assert not found[0] and found[1]
    v.close()


def test_ec_bulk_locate_matches_disk_search(tmp_path):
    random.seed(23)
    v = Volume(str(tmp_path), "", 3)
    keys = _fill_volume(v, 120)
    v.close()
    base = v.file_name()
    write_ec_files(
        base, large_block_size=LARGE_BLOCK, small_block_size=SMALL_BLOCK
    )
    write_sorted_file_from_idx(base)

    ev = EcVolume(str(tmp_path), "", 3)
    probes = np.array(keys + [3, 5], dtype=np.uint64)
    off_dev, size_dev, found_dev = ev.bulk_locate(probes)
    off_cpu, size_cpu, found_cpu = ev.bulk_locate(probes, use_device=False)
    assert np.array_equal(found_dev, found_cpu)
    assert np.array_equal(off_dev, off_cpu)
    assert np.array_equal(size_dev, size_cpu)
    assert found_dev[: len(keys)].all()
    assert not found_dev[len(keys) :].any()

    # tombstoning through the ecx must invalidate the device snapshot
    ev.delete_needle_from_ecx(keys[0])
    _, _, found = ev.bulk_locate(probes)
    assert not found[0] and found[1]
    ev.close()


def test_volume_server_bulk_rpcs(tmp_path):
    from tests.test_cluster import Cluster

    from seaweedfs_tpu.client import assign
    from seaweedfs_tpu.client.operation import (
        batch_read,
        bulk_lookup,
        upload_data,
    )

    async def body():
        random.seed(31)
        cluster = Cluster(tmp_path, n_volume_servers=1)
        await cluster.start()
        try:
            async with aiohttp.ClientSession() as session:
                payloads = {}
                vid = None
                server = None
                from seaweedfs_tpu.storage.file_id import FileId

                for i in range(20):
                    ar = await assign(cluster.master.address)
                    data = random.randbytes(64 + i)
                    await upload_data(session, ar.url, ar.fid, data)
                    fid = FileId.parse(ar.fid)
                    if vid is None:
                        vid, server = fid.volume_id, ar.url
                    if fid.volume_id == vid:
                        payloads[fid.key] = data

                keys = sorted(payloads) + [999999999]
                offsets, sizes, found = await bulk_lookup(server, vid, keys)
                assert found[: len(payloads)].all()
                assert not found[-1]

                datas = await batch_read(server, vid, keys)
                for i, k in enumerate(sorted(payloads)):
                    assert datas[i] == payloads[k]
                assert datas[-1] is None
        finally:
            await cluster.stop()

    asyncio.run(body())
