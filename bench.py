"""North-star benchmarks: RS(10,4) ec.encode throughput + bulk needle-index
lookup QPS on TPU vs CPU baselines.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"},
where "extra" carries the secondary metrics (BASELINE.json configs 3 & 4).

- ec.encode TPU number: steady-state Pallas GF(2^8) encode over HBM-resident
  packed stripe batches (the BASELINE.json batched-multi-volume
  configuration). Timing uses K-run slope with a host digest pull per
  measurement, because block_until_ready on tunneled backends can return
  before execution completes — the slope between K=8 and K=64 cancels the
  constant RTT.
- ec.encode CPU baseline: the same encode via the native C++ PSHUFB
  nibble-table kernel capped at the AVX2 tier, single-threaded — the same
  technique as the reference's vendored klauspost/reedsolomon v1.9.2
  (pre-GFNI; ref: ec_encoder.go:120-136, go.mod:45; BASELINE.md notes the
  reference publishes no ec.encode number, so we measure the strongest
  honest equivalent on this host). The shipping host codec's GFNI tier is
  reported separately as ec.encode.host_kernel. Falls back to the numpy
  table path when no C++ toolchain is available.
- needle_lookup TPU number: 10M fid probes against a 10M-entry device-
  resident IndexSnapshot (the Volume.bulk_lookup serving path) as one
  batched branchless binary search; slope-timed like the encode.
- needle_lookup CPU baseline: the same probes through CompactMap.get — the
  per-request search the reference serves reads from
  (ref: compact_map.go:145-172), measured on a 1M-probe subset.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

import numpy as np


def baseline_mat_apply():
    """The reference-equivalent CPU matmul: the PSHUFB-tier (AVX2-capped)
    build of the native kernel — the technique of the reference's vendored
    klauspost/reedsolomon v1.9.2 (go.mod:45), which predates GFNI. The
    shipping NativeRSCodec's GFNI tier is measured AGAINST this, never AS
    this. Falls back to the best native tier, then numpy tables, when the
    capped build is unavailable."""
    try:
        from seaweedfs_tpu import native

        if native.load_baseline() is not None:
            return native.gf_matmul_baseline
    except Exception:
        pass
    from seaweedfs_tpu.tpu.coder import get_codec

    return get_codec("cpu")._mat_apply


class _BaselineCodecShim:
    """CpuRSCodec-shaped encode() over baseline_mat_apply for
    measure_cpu_baseline."""

    def __init__(self, parity_matrix):
        self._apply = baseline_mat_apply()
        self._m = parity_matrix

    def encode(self, data):
        return self._apply(self._m, data)


def measure_cpu_baseline(codec, data: np.ndarray, min_seconds: float = 1.0) -> float:
    """GB/s of data encoded by the numpy single-thread path."""
    codec.encode(data[:, : 1 << 16])  # warm tables
    n_bytes = data.size
    iters = 0
    t0 = time.perf_counter()
    while True:
        codec.encode(data)
        iters += 1
        dt = time.perf_counter() - t0
        if dt >= min_seconds and iters >= 2:
            return n_bytes * iters / dt / 1e9


def _measured_gbps(
    encode_fn, packed, n_bytes: int, k_lo: int = 8, k_hi: int = 64,
    reps: int = 5,
) -> float:
    """Shared device-timing harness: jit, compile+warm through a scalar
    digest (forces the whole FIFO queue to drain — the only trustworthy
    timing discipline over the tunnel's RTT noise), then slope-time."""
    import jax
    import jax.numpy as jnp

    encode = jax.jit(encode_fn)
    digest = jax.jit(lambda x: x.sum(dtype=jnp.uint32))
    _ = np.asarray(digest(encode(packed)))  # compile + warm

    def run(k: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = encode(packed)
        _ = np.asarray(digest(out))
        return time.perf_counter() - t0

    return n_bytes / _slope_time(run, k_lo=k_lo, k_hi=k_hi, reps=reps) / 1e9


def measure_tpu(parity_matrix, packed_np: np.ndarray) -> float:
    """GB/s of data encoded on device (slope-timed)."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.gf256 import gf_matmul_packed

    packed = jax.device_put(jnp.asarray(packed_np))
    return _measured_gbps(
        lambda p: gf_matmul_packed(parity_matrix, p),
        packed,
        packed_np.size * 4,
    )


def measure_kernel_roofline(parity_matrix, packed_np: np.ndarray) -> dict:
    """Write the kernel's ceiling DOWN instead of asserting it (VERDICT r4
    item 5): measure both xtime formulations on the same HBM-resident
    stripe batch, convert to i32 ops/s via the statically-counted op count,
    and compare against the machine's nominal roofs.

    v5e nominal roofs (public spec): ~819 GB/s HBM; VPU ~= 8 sublanes x
    128 lanes x 4 ALUs x ~0.94 GHz ~= 3.9e12 i32 ops/s. HBM traffic per
    input byte at RS(10,4) is 1.4 (read 10 rows, write 4)."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.gf256 import count_expr_ops, gf_matmul_packed

    # a 4MB-per-row slice (40MB batch) is plenty for a steady-state ratio
    # and keeps the CPU stand-in path from eating minutes of bench budget
    packed_np = packed_np[:, : min(packed_np.shape[1], 1 << 20)]
    packed = jax.device_put(jnp.asarray(packed_np))
    n_bytes = packed_np.size * 4

    VPU_PEAK = 3.9e12
    HBM_PEAK = 819e9
    out: dict = {
        "vpu_nominal_ops_per_s": VPU_PEAK,
        "hbm_nominal_gbps": HBM_PEAK / 1e9,
        # the roofs are v5e's: fractions are only meaningful when the
        # legs actually ran on the TPU, not a CPU stand-in
        "valid": jax.devices()[0].platform == "tpu",
    }
    best_mode, best_gbps = None, 0.0
    for mode in ("mul", "shift"):
        gbps = _measured_gbps(
            lambda p, m=mode: gf_matmul_packed(parity_matrix, p, xtime_mode=m),
            packed, n_bytes, k_lo=4, k_hi=16, reps=3,
        )
        ops_per_word_col = count_expr_ops(parity_matrix, mode)
        ops_per_input_byte = ops_per_word_col / (
            4 * parity_matrix.shape[1]
        )
        ops_per_s = gbps * 1e9 * ops_per_input_byte
        out[mode] = {
            "gbps": round(gbps, 3),
            "ops_per_input_byte": round(ops_per_input_byte, 2),
            "i32_ops_per_s": round(ops_per_s / 1e12, 3),  # tera-ops
            "vpu_fraction": round(ops_per_s / VPU_PEAK, 3),
            "hbm_fraction": round(gbps * 1.4 * 1e9 / HBM_PEAK, 3),
        }
        if gbps > best_gbps:
            best_mode, best_gbps = mode, gbps
    m = out[best_mode]
    out["bottleneck"] = (
        "VPU" if m["vpu_fraction"] > m["hbm_fraction"] else "HBM"
    )
    out["best_mode"] = best_mode
    out["mul_vs_shift"] = round(
        out["mul"]["gbps"] / max(out["shift"]["gbps"], 1e-9), 2
    )
    return out


def measure_mxu_bitslice(parity_matrix, packed_np: np.ndarray) -> dict:
    """MXU bit-slice prototype vs the packed VPU kernel, same batch,
    slope-timed (VERDICT r4 item 5). Answers whether routing the GF(2^8)
    matmul through the MXU (binary matmul over bit planes) beats the VPU
    xtime formulation — the prototype's earlier out-of-tree measurement
    (~63 GB/s, on par) is now reproducible from the tree."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.gf256 import (
        gf_matmul_bitsliced,
        gf_matmul_packed,
    )

    packed_np = packed_np[:, : min(packed_np.shape[1], 1 << 20)]
    packed = jax.device_put(jnp.asarray(packed_np))
    n_bytes = packed_np.size * 4

    out: dict = {"bytes": n_bytes}
    for name, fn in (
        ("bitslice", lambda p: gf_matmul_bitsliced(parity_matrix, p)),
        ("packed", lambda p: gf_matmul_packed(parity_matrix, p)),
    ):
        out[f"{name}_gbps"] = round(
            _measured_gbps(fn, packed, n_bytes, k_lo=2, k_hi=8, reps=3), 3
        )
    out["vs_packed"] = round(
        out["bitslice_gbps"] / max(out["packed_gbps"], 1e-9), 2
    )
    return out


def measure_mxu_bitslice_identity(width: int = 1 << 16) -> dict:
    """Identity-check the MXU bit-slice GF(2^8) matmul against the table
    codec on every supported geometry (ISSUE 17). Runs on ANY jax backend
    (the bitplane formulation is backend-agnostic), so the check holds on
    the CPU stand-in even while throughput is only meaningful on a TPU —
    a silent formulation regression can't hide behind the relay being
    down. Returns {"geometries": {"10.4": bool, ...}, "all_identical":
    bool, "width": width}."""
    from seaweedfs_tpu.ops.gf256 import (
        gf_matmul_bitsliced,
        pack_bytes_host,
        unpack_bytes_host,
    )
    from seaweedfs_tpu.storage.erasure_coding.coder_cpu import CpuRSCodec

    rng = np.random.default_rng(0x17)
    geoms = {}
    for k, m in ((10, 4), (6, 3), (12, 4)):
        codec = CpuRSCodec(k, m)
        data = rng.integers(0, 256, size=(k, width), dtype=np.uint8)
        want = codec.encode(data)
        got = unpack_bytes_host(
            np.asarray(
                gf_matmul_bitsliced(codec.parity_matrix, pack_bytes_host(data))
            ),
            width,
        )
        geoms[f"{k}.{m}"] = bool(np.array_equal(want, got))
    return {
        "geometries": geoms,
        "all_identical": all(geoms.values()),
        "width": width,
    }


def measure_sharded_ec(n_volumes: int = 8, width: int = 1 << 20) -> dict:
    """Benched multi-chip mesh legs (ISSUE 17): encode AND rebuild through
    parallel/sharded_ec over the (vol, blk) device mesh, identity-checked
    against the table codec, scored as mesh-vs-1-device scaling of the
    SAME shard_map formulation. Off-TPU the parent runner forces
    --xla_force_host_platform_device_count so the mesh is 4 virtual host
    devices on however many cores exist — that proves the mesh path's
    correctness and dispatch overhead, not real scale-out, which is why
    every entry carries device_status and the mesh shape."""
    import jax

    from seaweedfs_tpu.parallel.sharded_ec import (
        make_mesh,
        sharded_encode,
        sharded_reconstruct_padded,
    )
    from seaweedfs_tpu.storage.erasure_coding.coder_cpu import CpuRSCodec
    from seaweedfs_tpu.storage.erasure_coding.galois import (
        reconstruction_matrix,
    )

    codec = CpuRSCodec(10, 4)
    devs = jax.devices()
    mesh = make_mesh()
    mesh_1 = make_mesh(n_devices=1)
    out: dict = {
        "n_devices": len(devs),
        "platform": devs[0].platform,
        "mesh_shape": dict(mesh.shape),
        "n_volumes": n_volumes,
        "width": width,
    }
    rng = np.random.default_rng(0x5EC)
    data = rng.integers(
        0, 256, size=(n_volumes, 10, width), dtype=np.uint8
    )
    in_bytes = data.size

    # --- encode: identity on volume 0, then mesh vs 1-device timing ---
    parity = np.asarray(sharded_encode(codec.parity_matrix, data, mesh))
    out["encode_identical"] = bool(
        np.array_equal(parity[0], codec.encode(data[0]))
    )
    for name, m in (("mesh", mesh), ("1dev", mesh_1)):
        jax.block_until_ready(
            sharded_encode(codec.parity_matrix, data, m)
        )  # warm the jit cache for this mesh
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(
                sharded_encode(codec.parity_matrix, data, m)
            )
            best = min(best, time.perf_counter() - t0)
        out[f"encode_gbps_{name}"] = round(in_bytes / best / 1e9, 3)
    out["encode_scaling"] = round(
        out["encode_gbps_mesh"] / max(out["encode_gbps_1dev"], 1e-9), 2
    )

    # --- rebuild: lose shards [0, 1, 11, 13], decode from 10 survivors ---
    all_shards = np.concatenate([data, parity], axis=1)
    missing = [0, 1, 11, 13]
    survivors = [i for i in range(14) if i not in missing][:10]
    dec = reconstruction_matrix(codec.matrix, survivors)
    dec_rows = dec[np.asarray([0, 1])]  # the lost DATA rows
    surv = np.ascontiguousarray(all_shards[:, survivors, :])
    got = sharded_reconstruct_padded(dec_rows, surv, mesh)
    out["rebuild_identical"] = bool(
        np.array_equal(got[:, 0], data[:, 0])
        and np.array_equal(got[:, 1], data[:, 1])
    )
    for name, m in (("mesh", mesh), ("1dev", mesh_1)):
        sharded_reconstruct_padded(dec_rows, surv, m)  # warm the jit cache
        # (sharded_reconstruct_padded returns a materialized np array, so
        # no block_until_ready is needed on either side of the timer)
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            sharded_reconstruct_padded(dec_rows, surv, m)
            best = min(best, time.perf_counter() - t0)
        out[f"rebuild_gbps_{name}"] = round(in_bytes / best / 1e9, 3)
    out["rebuild_scaling"] = round(
        out["rebuild_gbps_mesh"] / max(out["rebuild_gbps_1dev"], 1e-9), 2
    )
    return out


def measure_multi_device(
    n_volumes: int = 64,
    shard_bytes: int = 128 << 10,
    k_lo: int = 8,
    k_hi: int = 64,
) -> dict:
    """Device-side multi-volume batching (BASELINE.json config 3's core
    claim): encoding V volumes as ONE wide [10, V*W] dispatch — GF columns
    are independent, so concatenating volumes along the stripe axis is
    byte-exact (the same trick write_ec_files_multi's device path uses) —
    vs V separate [10, W] dispatches of the same kernel. HBM-resident both
    ways; slope-timed. The default shape is the launch-bound regime
    (many small volumes — the EC small-block world) where batching is
    the difference between ~3 and ~65+ GB/s; at >=20MB per dispatch the
    per-volume leg already amortizes launches and batching is ~1x.
    (A vmapped [V,10,W] formulation was measured ~2x SLOWER than either
    — vmap tiles the kernel worse — and a sliced `packed[v]` per-volume
    leg pays a hidden gather dispatch per volume; both pitfalls are
    avoided here.)"""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.gf256 import gf_matmul_packed, pack_bytes_host
    from seaweedfs_tpu.storage.erasure_coding.galois import build_matrix

    parity_matrix = build_matrix(10, 14)[10:]
    rng = np.random.default_rng(7)
    data = rng.integers(
        0, 256, size=(n_volumes, 10, shard_bytes), dtype=np.uint8
    )
    packed_np = np.stack([pack_bytes_host(v) for v in data])
    # volumes side by side along the packed-word axis: one wide dispatch
    wide_np = np.concatenate(list(packed_np), axis=1)
    wide_dev = jax.device_put(jnp.asarray(wide_np))
    n_bytes = packed_np.size * 4

    one = jax.jit(lambda p: gf_matmul_packed(parity_matrix, p))
    digest = jax.jit(lambda x: x.sum(dtype=jnp.uint32))

    _ = np.asarray(digest(one(wide_dev)))  # compile + warm (wide shape)
    vols = [
        jax.device_put(jnp.asarray(packed_np[v])) for v in range(n_volumes)
    ]
    _ = np.asarray(digest(one(vols[0])))  # compile + warm (narrow shape)

    def run_wide(k: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = one(wide_dev)
        _ = np.asarray(digest(out))
        return time.perf_counter() - t0

    def run_seq(k: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            for v in vols:
                out = one(v)
        _ = np.asarray(digest(out))
        return time.perf_counter() - t0

    wide_gbps = n_bytes / _slope_time(run_wide, k_lo, k_hi) / 1e9
    seq_gbps = n_bytes / _slope_time(run_seq, k_lo, k_hi) / 1e9
    return {
        "n_volumes": n_volumes,
        "bytes": n_bytes,
        "wide_gbps": round(wide_gbps, 3),
        "per_volume_dispatch_gbps": round(seq_gbps, 3),
        "batch_speedup": round(wide_gbps / max(seq_gbps, 1e-9), 2),
        # stand-in runs self-invalidate (VERDICT §4): GB/s measured on a
        # CPU stand-in says nothing about the device batch dimension
        "valid": jax.devices()[0].platform == "tpu",
    }


def measure_memcpy_roofline(size_mb: int = 256) -> float:
    """Host one-way memcpy GB/s — the bandwidth roofline every host-side
    e2e pipeline divides into (read + data write + parity write per
    source byte)."""
    a = np.random.default_rng(3).integers(
        0, 256, size_mb << 20, dtype=np.uint8
    )
    b = np.empty_like(a)
    b[:] = a  # fault pages
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        b[:] = a
        best = min(best, time.perf_counter() - t0)
    return len(a) / best / 1e9


def _slope_time(run, k_lo: int = 8, k_hi: int = 64, reps: int = 5) -> float:
    """Per-iteration seconds from the K-run slope (cancels constant RTT)."""
    run(2)  # warm the pull path
    t_lo = min(run(k_lo) for _ in range(reps))
    t_hi = min(run(k_hi) for _ in range(reps))
    per_iter = (t_hi - t_lo) / (k_hi - k_lo)
    if per_iter <= 0:  # RTT noise swamped the slope; fall back to bulk timing
        per_iter = t_hi / k_hi
    return per_iter


def measure_lookup(
    n_entries: int = 10_000_000, n_probes: int = 10_000_000
) -> tuple[float, float]:
    """-> (tpu_qps, cpu_qps) for bulk fid->(offset,size) probes."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.index_kernel import (
        IndexSnapshot,
        _bulk_lookup,
        _bulk_lookup_bucketed,
        _split_u64,
    )
    from seaweedfs_tpu.storage.needle_map import CompactMap

    rng = np.random.default_rng(2)
    gaps = rng.integers(1, 20, size=n_entries, dtype=np.uint64)
    keys = np.cumsum(gaps).astype(np.uint64)  # sorted unique
    offsets = rng.integers(1, 1 << 30, size=n_entries, dtype=np.uint64).astype(
        np.uint32
    )
    sizes = rng.integers(1, 1 << 20, size=n_entries, dtype=np.uint64).astype(
        np.uint32
    )
    probes = keys[rng.integers(0, n_entries, size=n_probes)]

    # --- device path: table + probes HBM-resident, slope-timed ---
    snap = IndexSnapshot(keys, offsets, sizes)
    phi, plo = _split_u64(probes)
    phi_d = jax.device_put(jnp.asarray(phi))
    plo_d = jax.device_put(jnp.asarray(plo))
    digest = jax.jit(lambda o, s, f: o.sum(dtype=jnp.uint32))

    if snap.starts is not None:
        b_d = jax.device_put(jnp.asarray(snap._bucket_of(probes)))

        def encode_once():
            return _bulk_lookup_bucketed(
                snap.bsteps,
                snap.khi,
                snap.klo,
                snap.offsets,
                snap.sizes,
                snap.starts,
                phi_d,
                plo_d,
                b_d,
            )

    else:

        def encode_once():
            return _bulk_lookup(
                snap.steps,
                snap.khi,
                snap.klo,
                snap.offsets,
                snap.sizes,
                phi_d,
                plo_d,
            )

    _ = np.asarray(digest(*encode_once()))  # compile + warm

    def run(k: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = encode_once()
        _ = np.asarray(digest(*out))
        return time.perf_counter() - t0

    tpu_qps = n_probes / _slope_time(run, k_lo=2, k_hi=10, reps=3)

    # --- CPU baseline: CompactMap.get per probe (1M subset) ---
    sub = min(n_entries, 1_000_000)
    cm = CompactMap()
    set_ = cm.set
    for k, o, s in zip(
        keys[:sub].tolist(), offsets[:sub].tolist(), sizes[:sub].tolist()
    ):
        set_(k, o, s)
    cpu_probe_keys = [int(k) for k in keys[rng.integers(0, sub, size=sub)]]
    get = cm.get
    t0 = time.perf_counter()
    for k in cpu_probe_keys:
        get(k)
    cpu_qps = len(cpu_probe_keys) / (time.perf_counter() - t0)
    return tpu_qps, cpu_qps


def measure_lookup_gate_decomposition(
    n_entries: int = 1_000_000,
    batch_sizes: tuple = (64, 1024, 65536),
) -> dict:
    """Separate per-dispatch RTT from on-device kernel time for the
    serving lookup gate (VERDICT r4 item 6).

    The honest tunnel number (read_qps_batched_device ~7 QPS in r4) says
    nothing about whether the DESIGN works on a locally-attached chip,
    because every batch pays the tunnel's RTT and its ~0.03 GB/s download
    leg. This measures, per batch size B in {64, 1k, 64k}:
      - t_e2e: one full host->device->host `IndexSnapshot.lookup` dispatch
        (the serving path, best-of-N: single dispatches are RTT-noisy)
      - t_kern: device-resident probes, scalar digest pull, slope-timed —
        the kernel's own time without transfers
    and derives rtt (t_e2e - t_kern at B=64), the kernel's us/1k-probe
    slope, and a PROJECTED locally-attached QPS under stated assumptions
    (100us local dispatch overhead, 8 GB/s host link) — clearly labelled a
    projection, not a measurement."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.index_kernel import IndexSnapshot, _split_u64

    rng = np.random.default_rng(5)
    gaps = rng.integers(1, 20, size=n_entries, dtype=np.uint64)
    keys = np.cumsum(gaps).astype(np.uint64)
    offsets = rng.integers(1, 1 << 30, size=n_entries, dtype=np.uint64).astype(
        np.uint32
    )
    sizes = rng.integers(1, 1 << 20, size=n_entries, dtype=np.uint64).astype(
        np.uint32
    )
    snap = IndexSnapshot(keys, offsets, sizes)
    digest = jax.jit(lambda o, s, f: o.sum(dtype=jnp.uint32))

    batches: dict = {}
    sizes_b = tuple(batch_sizes)
    for B in sizes_b:
        probes = keys[rng.integers(0, n_entries, size=B)]
        snap.lookup(probes)  # compile + warm this padded shape
        t_e2e = float("inf")
        for _ in range(7):
            t0 = time.perf_counter()
            snap.lookup(probes)
            t_e2e = min(t_e2e, time.perf_counter() - t0)

        phi, plo = _split_u64(probes)
        phi_d = jax.device_put(jnp.asarray(phi))
        plo_d = jax.device_put(jnp.asarray(plo))
        if snap.starts is not None:
            from seaweedfs_tpu.ops.index_kernel import _bulk_lookup_bucketed

            b_d = jax.device_put(jnp.asarray(snap._bucket_of(probes)))

            def enc():
                return _bulk_lookup_bucketed(
                    snap.bsteps, snap.khi, snap.klo, snap.offsets,
                    snap.sizes, snap.starts, phi_d, plo_d, b_d,
                )

        else:
            from seaweedfs_tpu.ops.index_kernel import _bulk_lookup

            def enc():
                return _bulk_lookup(
                    snap.steps, snap.khi, snap.klo, snap.offsets,
                    snap.sizes, phi_d, plo_d,
                )

        _ = np.asarray(digest(*enc()))  # warm

        def run(k: int) -> float:
            t0 = time.perf_counter()
            out = None
            for _ in range(k):
                out = enc()
            _ = np.asarray(digest(*out))
            return time.perf_counter() - t0

        t_kern = _slope_time(run, k_lo=4, k_hi=32, reps=3)
        batches[B] = {
            "t_e2e_ms": round(t_e2e * 1e3, 3),
            "t_kernel_ms": round(t_kern * 1e3, 3),
        }

    b_lo, b_hi = sizes_b[0], sizes_b[-1]
    kern_per_probe = (
        batches[b_hi]["t_kernel_ms"] - batches[b_lo]["t_kernel_ms"]
    ) / 1e3 / (b_hi - b_lo)
    rtt_s = max(
        0.0, (batches[b_lo]["t_e2e_ms"] - batches[b_lo]["t_kernel_ms"]) / 1e3
    )
    # projection assumptions, stated in the artifact: a locally-attached
    # chip pays ~100us dispatch overhead and moves probe/result bytes at
    # ~8 GB/s over the host link (28 B/probe: 16 in, 12 out)
    local_dispatch_s = 100e-6
    local_bw = 8e9
    proj = {}
    for B in sizes_b[1:]:
        t_local = (
            local_dispatch_s
            + batches[B]["t_kernel_ms"] / 1e3
            + B * 28 / local_bw
        )
        proj[str(B)] = round(B / t_local)
    valid = jax.devices()[0].platform == "tpu"
    return {
        "n_entries": n_entries,
        "batches": batches,
        "device_rtt_ms": round(rtt_s * 1e3, 2),
        "device_kernel_us_per_1k": round(kern_per_probe * 1e6 * 1000, 2),
        "projected_local_qps": proj,
        # stand-in runs self-invalidate (VERDICT §4): a projection built
        # from CPU stand-in kernel time is not a device projection
        "valid": valid,
        "note": (
            "projected_local_qps is a PROJECTION for a locally-"
            "attached chip (100us dispatch, 8 GB/s link assumed), from "
            "measured on-device kernel time; t_e2e is measured through "
            "the tunnel"
            if valid
            else "INVALID AS A DEVICE NUMBER: projection from CPU "
            "stand-in kernel time (no TPU answered this run); the "
            "numbers characterize the stand-in host, not the chip"
        ),
    }


def measure_needle_map_device_lookup(
    n_volumes: int = 4,
    entries_per_volume: int = 40_000,
    window_s: float = 1.2,
    concurrency: int = 256,
    seed: int = 18,
) -> dict:
    """The MEASURED metadata device-lookup leg (ISSUE 18), superseding
    `lookup_gate.decomposition`'s projection: real multi-run LSM needle
    maps behind the REAL `BatchLookupGate` seam, the arena backend
    scored against the host backend on the same seeded workload in the
    same credit window, entry-wise identity asserted in-leg (the gate's
    identity check re-derives EVERY device answer from the host map),
    and the ragged kernel's stage walls (pack/upload/dispatch/readback)
    measured at the batch-size distribution the gate itself produced
    under concurrent load — not at round numbers someone liked.
    """
    import asyncio
    import shutil
    import tempfile

    from seaweedfs_tpu.ops.ragged_lookup import DeviceColumnArena
    from seaweedfs_tpu.server.lookup_gate import BatchLookupGate
    from seaweedfs_tpu.storage.needle_map.lsm_map import LsmNeedleMap

    rng = np.random.default_rng(seed)
    root = tempfile.mkdtemp(prefix="bench_devlookup_")

    from seaweedfs_tpu.types import TOMBSTONE_FILE_SIZE

    class _Vol:
        """Volume stand-in exposing exactly the two seams the gate
        probes: nm.get and Volume.bulk_lookup's HOST path (nm.get loop
        with tombstone filtering) — the real device path is the arena
        backend under test, not bulk_lookup's per-volume snapshot."""

        def __init__(self, nm):
            self.nm = nm

        def bulk_lookup(self, keys, use_device=None):
            offs = np.zeros(len(keys), dtype=np.uint32)
            szs = np.zeros(len(keys), dtype=np.uint32)
            fnd = np.zeros(len(keys), dtype=bool)
            get = self.nm.get
            for i, k in enumerate(keys.tolist()):
                nv = get(int(k))
                if (
                    nv is not None
                    and nv.offset_units != 0
                    and nv.size != TOMBSTONE_FILE_SIZE
                ):
                    offs[i] = nv.offset_units
                    szs[i] = nv.size
                    fnd[i] = True
            return offs, szs, fnd

    class _Store:
        def __init__(self):
            self.vols = {}

        def find_volume(self, vid):
            return self.vols.get(vid)

    store = _Store()
    oracle: dict = {}
    all_keys: dict = {}
    try:
        for vid in range(1, n_volumes + 1):
            # memtable sized so each volume seals ~5 runs (multi-run maps
            # are the case the bloom pre-filter exists for)
            nm = LsmNeedleMap(
                os.path.join(root, f"v{vid}.idx"),
                memtable_bytes=entries_per_volume * 120 // 5,
            )
            keys = rng.choice(
                np.arange(1, entries_per_volume * 16, dtype=np.uint64),
                size=entries_per_volume,
                replace=False,
            )
            chunk = max(1024, entries_per_volume // 7)
            for c0 in range(0, entries_per_volume, chunk):
                part = keys[c0 : c0 + chunk]
                nm.put_batch(
                    (int(k), c0 + j + 1, 100 + ((c0 + j) % 900))
                    for j, k in enumerate(part.tolist())
                )
            oracle.update(
                {
                    (vid, int(k)): (i + 1, 100 + (i % 900))
                    for i, k in enumerate(keys.tolist())
                }
            )
            for k in keys[:: max(1, entries_per_volume // 200)].tolist():
                nm.delete(int(k), 0)
                oracle.pop((vid, int(k)), None)
            store.vols[vid] = _Vol(nm)
            all_keys[vid] = keys
        run_counts = {
            vid: len(v.nm._runs) for vid, v in store.vols.items()
        }

        def probe_plan(n: int, miss_rate: float = 0.1):
            """Seeded (vid, key) sequence: mostly hits across all
            volumes, a slice of misses (the bloom pre-filter's case)."""
            vids = rng.integers(1, n_volumes + 1, size=n)
            out = []
            for vid in vids.tolist():
                ks = all_keys[vid]
                if rng.random() < miss_rate:
                    out.append((vid, int(rng.integers(1 << 40, 1 << 41))))
                else:
                    out.append((vid, int(ks[rng.integers(0, len(ks))])))
            return out

        def drive(gate, plan, concurrency: int, budget_s: float):
            """Same-loop concurrent probers (the gate's production
            shape): `concurrency` clients walk the shared seeded plan,
            each await lands in the gate's per-wakeup flush. Returns
            (per-probe latencies, probes done, elapsed)."""
            lat: list = []

            async def client(idx):
                i = idx
                t_end = time.perf_counter() + budget_s
                while time.perf_counter() < t_end:
                    vid, key = plan[i % len(plan)]
                    i += concurrency
                    t0 = time.perf_counter()
                    await gate.lookup(vid, key)
                    lat.append(time.perf_counter() - t0)

            async def main():
                await asyncio.gather(
                    *(client(i) for i in range(concurrency))
                )

            t0 = time.perf_counter()
            asyncio.run(main())
            return lat, len(lat), time.perf_counter() - t0

        plan = probe_plan(8192)

        # -- scrape the batch-size distribution the gate itself produces
        scrape_gate = BatchLookupGate(store)
        drive(scrape_gate, plan, concurrency=concurrency, budget_s=0.3)
        batch_hist = dict(sorted(scrape_gate.batch_hist.items()))

        # -- host backend window
        host_gate = BatchLookupGate(store)
        h_lat, h_n, h_wall = drive(
            host_gate, plan, concurrency=concurrency, budget_s=window_s
        )

        # -- arena backend window (scored): identity OFF here so the
        # credit-window comparison is production-config vs production-
        # config; the dedicated window below asserts identity on every
        # dispatch
        arena = DeviceColumnArena()
        dev_gate = BatchLookupGate(
            store, arena=arena, identity_check=False
        )
        # warm: register every volume's run set, then block on one
        # double-buffered upload (serving-path dispatches never block)
        for vid, v in store.vols.items():
            _hits, segs = v.nm.arena_view(all_keys[vid][:1])
            arena.ensure(segs)
        arena.refresh_sync()
        d_lat, d_n, d_wall = drive(
            dev_gate, plan, concurrency=concurrency, budget_s=window_s
        )

        # -- identity window: every dispatch re-derived from the host
        # map inside the gate, plus a dict-oracle pass on the results
        idg = BatchLookupGate(store, arena=arena, identity_check=True)
        drive(idg, plan, concurrency=concurrency, budget_s=min(0.4, window_s))

        def pct(xs, q):
            return float(np.percentile(np.asarray(xs), q)) if xs else 0.0

        # -- ragged kernel stage walls at the SCRAPED distribution
        sizes, weights = zip(*batch_hist.items())
        w = np.asarray(weights, dtype=np.float64)
        w /= w.sum()
        timings: dict = {}
        kern_probes = 0
        views = {
            vid: v.nm.arena_view(all_keys[vid][:1])[1]
            for vid, v in store.vols.items()
        }
        # ragged batches pre-built OUTSIDE the timed loop so the four
        # stage walls partition the dispatch wall (coverage_of_wall)
        n_disp = 24
        dispatch_batches = []
        for _ in range(n_disp):
            b = int(sizes[int(rng.choice(len(sizes), p=w))])
            groups: dict = {}
            for vid, key in probe_plan(b):
                groups.setdefault(vid, []).append(key)
            dispatch_batches.append(
                [
                    (views[vid], np.asarray(ks, dtype=np.uint64))
                    for vid, ks in groups.items()
                ]
            )
        t_kern0 = time.perf_counter()
        for gl in dispatch_batches:
            res = arena.probe_groups(gl, timings)
            kern_probes += sum(len(ks) for _s, ks in gl)
            if any(r is None for r in res):
                raise RuntimeError("arena went cold mid-bench")
        kern_wall = time.perf_counter() - t_kern0

        # -- entry-wise identity: gate answers vs the dict oracle
        oracle_checked = 0
        oracle_bad = 0
        # each drive() ran under its own asyncio.run loop; rebind the
        # gate before parking new futures on the fresh loop
        idg._loop = None

        async def oracle_pass():
            nonlocal oracle_checked, oracle_bad
            picks = probe_plan(2048)
            res = await asyncio.gather(
                *(idg.lookup(vid, k) for vid, k in picks)
            )
            for (vid, k), r in zip(picks, res):
                oracle_checked += 1
                if r != oracle.get((vid, k)):
                    oracle_bad += 1

        asyncio.run(oracle_pass())

        status = _device_status()
        p99_host = pct(h_lat, 99)
        p99_dev = pct(d_lat, 99)
        overhead = (p99_dev / p99_host) if p99_host else float("inf")
        overhead_ok = overhead <= 1.5
        identity_ok = (
            idg.stats["identity_mismatches"] == 0
            and idg.stats["device_batches"] > 0
            and oracle_bad == 0
        )
        stage_sum = sum(
            timings.get(k, 0.0)
            for k in ("pack_s", "upload_s", "dispatch_s", "readback_s")
        )
        stages = {
            k: round(timings.get(k, 0.0), 4)
            for k in ("pack_s", "upload_s", "dispatch_s", "readback_s")
        }
        stages["total_s"] = round(kern_wall, 4)
        # the four stages PARTITION each dispatch's wall (they are
        # sequential inside probe_groups); packing python + group
        # bookkeeping outside the timed stages keeps coverage < 1
        stages["coverage_of_wall"] = round(
            stage_sum / kern_wall, 3
        ) if kern_wall else 0.0
        return {
            "n_volumes": n_volumes,
            "entries_per_volume": entries_per_volume,
            "runs_per_volume": run_counts,
            "batch_size_dist": {str(k): v for k, v in batch_hist.items()},
            "host_gate": {
                "probes_per_s": round(h_n / h_wall) if h_wall else 0,
                "p50_ms": round(pct(h_lat, 50) * 1e3, 3),
                "p99_ms": round(p99_host * 1e3, 3),
                "probes": h_n,
            },
            "device_gate": {
                "probes_per_s": round(d_n / d_wall) if d_wall else 0,
                "p50_ms": round(pct(d_lat, 50) * 1e3, 3),
                "p99_ms": round(p99_dev * 1e3, 3),
                "probes": d_n,
                "device_batches": dev_gate.stats["device_batches"],
                "host_fallbacks": dev_gate.stats["host_fallbacks"],
            },
            "overhead_x_p99": round(overhead, 3),
            "overhead_ok": overhead_ok,
            "identity": {
                "checked_every_dispatch": True,
                "device_batches_checked": idg.stats["device_batches"],
                "gate_mismatches": idg.stats["identity_mismatches"],
                "oracle_checked": oracle_checked,
                "oracle_mismatches": oracle_bad,
                "ok": identity_ok,
            },
            "kernel": {
                "dispatches": n_disp,
                "probes_per_s": (
                    round(kern_probes / kern_wall) if kern_wall else 0
                ),
                "stage_breakdown": stages,
                "standin": status != "tpu",
            },
            "arena": arena.stats(),
            "device_status": status,
            # a stand-in run is still VALID as a gate-overhead proof
            # (same host serves both backends); only the kernel
            # throughput claim needs the chip
            "valid": identity_ok and (status == "tpu" or overhead_ok),
            "note": (
                "measured end-to-end through the real gate seam; "
                "identity asserted on every dispatch"
                if status == "tpu"
                else "gate overhead + identity measured on CPU stand-in "
                "(valid: same host serves both backends); kernel "
                "probes/s characterizes the stand-in, not the chip"
            ),
        }
    finally:
        for v in store.vols.values():
            try:
                v.nm.close()
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)


async def _drive_ping(
    http, hostport: str, n: int, concurrency: int, target: str = "/ping"
) -> dict:
    """The client half of the trivial-200 floor: n GETs at `concurrency`
    against an ALREADY-RUNNING trivial endpoint. Shared by
    `_trivial_ping_qps` (same-loop server) and the overload leg's
    cross-thread ping (server on its own loop)."""
    import asyncio
    from collections import deque

    q = deque(range(n))

    async def ping_client():
        while True:
            try:
                q.popleft()
            except IndexError:
                break
            st, _ = await http.request("GET", hostport, target)
            if st != 200:  # not assert: must survive python -O
                raise RuntimeError(f"ping returned {st}")

    await http.request("GET", hostport, target)  # warm
    t0 = time.perf_counter()
    await asyncio.gather(*(ping_client() for _ in range(concurrency)))
    dt = time.perf_counter() - t0
    return {
        "ping_qps": round(n / dt),
        "ping_us_per_req": round(dt / n * 1e6, 1),
    }


async def _trivial_ping_qps(http, n: int, concurrency: int) -> dict:
    """Serve a pre-rendered trivial 200 from a fresh fast-tier server and
    drive n GETs through `http` at the given concurrency ->
    {ping_qps, ping_us_per_req}. The ONE implementation of the
    trivial-200 floor, shared by serving_ping_ceiling and the open-loop
    leg's same-credit-window inline ping — two copies could diverge for
    implementation rather than credit-window reasons."""
    from seaweedfs_tpu.util.fasthttp import FastHTTPServer, render_response

    resp = render_response(200, b'{"ok": 1}')

    async def handler(req):
        return resp

    srv = FastHTTPServer(handler)
    await srv.start("127.0.0.1", 0)
    port = srv._server.sockets[0].getsockname()[1]
    try:
        return await _drive_ping(http, f"127.0.0.1:{port}", n, concurrency)
    finally:
        await srv.stop()


def measure_ping_ceiling(concurrency: int = 16, n: int = 20000) -> dict:
    """The serving stack's own request floor: fast-tier server + pooled
    protocol client exchanging a trivial 200 at c=16, next to a raw
    asyncio echo for the event-loop+socket floor. Makes the QPS numbers
    interpretable: (measured us/req − ping us/req) is handler+payload
    work; (ping − echo) is what the HTTP machinery itself costs."""
    import asyncio

    from seaweedfs_tpu.util.fasthttp import FastHTTPClient

    out: dict = {"concurrency": concurrency}

    async def run() -> None:
        # raw echo floor
        async def handle(r, w):
            while True:
                data = await r.read(4096)
                if not data:
                    break
                w.write(data)
                await w.drain()

        esrv = await asyncio.start_server(handle, "127.0.0.1", 0)
        eport = esrv.sockets[0].getsockname()[1]
        # plain deque work queues (not asyncio.Queue), matching the
        # serving benchmark client: the floor must pay the same per-op
        # client machinery the real legs pay, no more
        from collections import deque

        q = deque(range(n))

        async def echo_client():
            r, w = await asyncio.open_connection("127.0.0.1", eport)
            msg = b"x" * 200
            while True:
                try:
                    q.popleft()
                except IndexError:
                    break
                w.write(msg)
                await r.readexactly(len(msg))
            w.close()

        t0 = time.perf_counter()
        await asyncio.gather(*(echo_client() for _ in range(concurrency)))
        out["echo_us_per_rtt"] = round(
            (time.perf_counter() - t0) / n * 1e6, 1
        )
        esrv.close()

        # fast-tier HTTP ping (the shared trivial-200 floor helper)
        http = FastHTTPClient(pool_per_host=concurrency + 4)
        try:
            out.update(await _trivial_ping_qps(http, n, concurrency))
        finally:
            await http.close()

    asyncio.run(run())
    out["http_machinery_us"] = round(
        out["ping_us_per_req"] - out["echo_us_per_rtt"], 1
    )
    return out


def _measure_group_commit_wait(n: int = 600, conc: int = 16) -> dict:
    """Flush-wait of the fsync group-commit tier: c concurrent writers
    through a GroupCommitWorker on tmpfs, measuring enqueue->fsync'd wall
    per request plus the worker's adaptive batch stats."""
    import asyncio
    import shutil
    import tempfile

    from seaweedfs_tpu.storage.group_commit import GroupCommitWorker
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume

    d = tempfile.mkdtemp(
        prefix="bench_gc_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {"concurrency": conc, "writes": n}
    try:
        v = Volume(d, "", 11, create=True)
        try:

            async def run() -> None:
                gc = GroupCommitWorker(v)
                gc.start()
                seq = [0]
                waits: list[float] = []
                data = b"x" * 1024

                async def writer() -> None:
                    while seq[0] < n:
                        seq[0] += 1
                        nd = Needle(cookie=1, id=seq[0], data=data)
                        t0 = time.perf_counter()
                        await gc.write(nd)
                        waits.append(time.perf_counter() - t0)

                await asyncio.gather(*(writer() for _ in range(conc)))
                await gc.stop()
                waits.sort()
                out["flush_wait_p50_us"] = round(
                    waits[len(waits) // 2] * 1e6, 1
                )
                out["flush_wait_avg_us"] = round(
                    sum(waits) / len(waits) * 1e6, 1
                )
                out["batches"] = gc.stats["batches"]
                out["avg_batch"] = round(
                    gc.stats["requests"] / max(gc.stats["batches"], 1), 1
                )
                out["largest_batch"] = gc.stats["largest_batch"]

            asyncio.run(run())
        finally:
            v.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out


def measure_write_budget(
    serving: Optional[dict] = None, ping: Optional[dict] = None
) -> dict:
    """Itemized microsecond budget of the serving write path (ISSUE 2
    tentpole; extends VERDICT r4 item 2's 'publish the budget').

    Two layers:
    - unit_costs_us: each handler component timed standalone, best-of-3
      over thousands of reps — the per-request serialized CPU each write
      spends in that code.
    - attribution vs the LIVE p50 (when `serving` — a measure_serving_qps
      result dict — is given): the benchmark client partitions every
      write's wall time into assign-RPC / client-build / upload-RPC legs,
      so leg averages sum to the average write latency BY CONSTRUCTION
      and coverage_of_p50 states how much of the measured p50 the
      itemization explains. On this 1-core host the closed loop satisfies
      p50 ~= c x (serialized work per request), so each leg's wall is
      ~c x its unit cost plus socket/event-loop machinery (the ping floor
      measures that machinery per hop).
    """
    import tempfile

    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume
    from seaweedfs_tpu.types import VERSION3
    from seaweedfs_tpu.util.fasthttp import (
        build_multipart,
        parse_multipart,
        render_response,
    )

    def best_us(fn, n=5000) -> float:
        for _ in range(200):
            fn()
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best / n * 1e6

    unit: dict = {}
    data = b"x" * 1024
    n_obj = Needle(cookie=0x1234, id=42, data=data)
    unit["needle_to_bytes_us"] = round(best_us(
        lambda: n_obj.to_bytes(VERSION3)), 2)

    import shutil

    d = tempfile.mkdtemp(
        prefix="bench_budget_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    try:
        v = Volume(d, "", 9, create=True)
        try:
            seq = [0]

            def wr():
                seq[0] += 1
                v.write_needle(Needle(cookie=1, id=seq[0], data=data))

            unit["volume_write_needle_us"] = round(best_us(wr), 2)
        finally:
            v.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)

    body, ctype = build_multipart("file", data)
    ctype_b = ctype.encode()
    unit["parse_multipart_us"] = round(best_us(
        lambda: parse_multipart(body, ctype_b)), 2)

    # client-side request build: payload synthesis + multipart framing
    # (the bench writer's work between assign and send)
    from seaweedfs_tpu.command.benchmark import fake_payload

    unit["client_build_us"] = round(best_us(
        lambda: build_multipart("file", fake_payload(7, 1024))), 2)
    # response assembly on the server side (201 + JSON body)
    unit["response_render_us"] = round(best_us(
        lambda: render_response(
            201, b'{"name": "", "size": 1024, "eTag": "deadbeef"}'
        )), 2)

    from seaweedfs_tpu.util.fasthttp import FastHTTPProtocol, FastHTTPServer

    raw = (
        b"POST /9,0123456789ab HTTP/1.1\r\nHost: h\r\n"
        b"Content-Length: %d\r\n\r\n" % len(body) + body
    )

    class _T:
        def pause_reading(self):
            pass

        def resume_reading(self):
            pass

        def is_closing(self):
            return False

    proto = FastHTTPProtocol(FastHTTPServer(None))
    proto.transport = _T()

    def parse():
        proto.buf += raw
        proto._try_parse()

    unit["http_parse_us"] = round(best_us(parse), 2)

    out: dict = {"unit_costs_us": unit}
    out["unit_sum_us"] = round(sum(unit.values()), 1)
    try:
        out["group_commit"] = _measure_group_commit_wait()
    except Exception as e:
        out["group_commit"] = {"error": str(e)[:120]}

    legs = (serving or {}).get("write_legs")
    lat = (serving or {}).get("write_latency") or {}
    if legs and lat.get("p50_ms"):
        p50_us = lat["p50_ms"] * 1000.0
        # the p50-coverage components use each leg's own p50 where the
        # 0.1ms latency buckets can resolve it (the upload leg, which
        # dominates) and the leg average below that resolution (assign/
        # build, tens of µs): summing averages against the p50 would let
        # a heavy tail inflate coverage past what the median's mass
        # actually explains
        comp = {
            "assign_rpc_us": (
                legs["assign_p50_us"] or legs["assign_avg_us"]
            ),
            "client_build_us": (
                legs["build_p50_us"] or legs["build_avg_us"]
            ),
            "upload_rpc_us": (
                legs["upload_p50_us"] or legs["upload_avg_us"]
            ),
        }
        out["components_us"] = comp
        out["component_sum_us"] = round(sum(comp.values()), 1)
        # avg-based sum alongside: legs partition each request, so this
        # reconciles with write_avg_us by construction (a self-check that
        # the instrumentation lost nothing)
        out["component_sum_avg_us"] = round(
            legs["assign_avg_us"]
            + legs["build_avg_us"]
            + legs["upload_avg_us"],
            1,
        )
        out["write_p50_us"] = round(p50_us, 1)
        out["write_avg_us"] = round(lat.get("avg_ms", 0) * 1000.0, 1)
        out["coverage_of_p50"] = round(
            out["component_sum_us"] / max(p50_us, 1e-9), 3
        )
        out["assign_amortization"] = {
            "assign_rpcs": legs["assign_rpcs"],
            "assign_batch": legs["assign_batch"],
        }
        if ping and ping.get("ping_us_per_req"):
            # the measured-floor argument, every component named: a write
            # is (1 + 1/batch) ping-equivalent HTTP hops plus the itemized
            # handler CPU; on this 1-core closed loop QPS ~= 1e6 / that
            p_us = ping["ping_us_per_req"]
            batch = max(legs["assign_batch"], 1)
            hops = 1.0 + 1.0 / batch
            floor_us = p_us * hops + out["unit_sum_us"]
            out["measured_floor"] = {
                "ping_us_per_req": p_us,
                "ping_equivalent_hops": round(hops, 3),
                "hop_components_us": round(p_us * hops, 1),
                "handler_unit_sum_us": out["unit_sum_us"],
                "floor_us_per_write": round(floor_us, 1),
                "floor_write_qps": round(1e6 / floor_us),
                "model": "write = 1 upload hop + 1/assign_batch assign "
                "hop (each = serving_ping_ceiling's us/req: socket + "
                "event loop + HTTP machinery) + handler unit CPU "
                "(unit_costs_us: http parse, multipart parse, needle "
                "serialize, volume append, response render, client "
                "build); remaining gap to the measured QPS is benchmark-"
                "client response handling + scheduler queueing",
            }
        out["note"] = (
            "components are the benchmark client's own partition of every "
            "write's wall time (assign RPC | request build | upload RPC), "
            "measured in the same c=16 run as the p50: per-leg p50 where "
            "the 0.1ms buckets resolve it, leg average below that. "
            "component_sum_avg_us reconciles with write_avg_us by "
            "construction (the legs partition each request); "
            "coverage_of_p50 states the itemized share of the p50. "
            "unit_costs_us are the standalone per-request CPU costs of "
            "the upload leg's handler components; upload_rpc ~= c x "
            "(unit costs + socket/event-loop machinery per hop, see "
            "serving_ping_ceiling). group_commit reports the fsync "
            "tier's flush wait separately."
        )
    else:
        out["component_sum_us"] = out["unit_sum_us"]
        out["note"] = (
            "no live serving sample available this run: unit costs only "
            "(assign RPC + 2x(socket send/recv + event-loop wakeups) + "
            "client side are the remainder of a measured write p50)"
        )
    return out


def measure_rebuild() -> tuple[float, float]:
    """ec.rebuild throughput (BASELINE.json config 2): reconstruct 4 lost
    shards (2 data + 2 parity) from 10 survivors — the same constant-matrix
    GF(2^8) primitive as encode, with the survivor-inverse matrix
    (ref ec_encoder.go:233-287). -> (tpu_gbps, cpu_gbps) over survivor
    bytes processed."""
    from seaweedfs_tpu.ops.gf256 import pack_bytes_host
    from seaweedfs_tpu.storage.erasure_coding.galois import (
        build_matrix,
        mat_mul,
        reconstruction_matrix,
    )
    from seaweedfs_tpu.tpu.coder import get_codec

    matrix = build_matrix(10, 14)
    missing = [0, 1, 11, 13]
    survivors = [i for i in range(14) if i not in missing][:10]
    dec = reconstruction_matrix(matrix, survivors)
    rec_rows = np.concatenate(
        [dec[np.asarray([0, 1])], mat_mul(matrix[np.asarray([11, 13])], dec)]
    )

    rng = np.random.default_rng(5)
    cpu_data = rng.integers(0, 256, size=(10, 4 << 20), dtype=np.uint8)
    apply_fn = baseline_mat_apply()  # reference-equivalent PSHUFB tier
    apply_fn(rec_rows, cpu_data[:, : 1 << 16])  # warm
    n_bytes = cpu_data.size
    iters = 0
    t0 = time.perf_counter()
    while True:
        apply_fn(rec_rows, cpu_data)
        iters += 1
        dt = time.perf_counter() - t0
        if dt >= 1.0 and iters >= 2:
            cpu_gbps = n_bytes * iters / dt / 1e9
            break

    data = rng.integers(0, 256, size=(10, 16 << 20), dtype=np.uint8)
    tpu_gbps = measure_tpu(rec_rows, pack_bytes_host(data))
    return tpu_gbps, cpu_gbps


def measure_rebuild_e2e(size_bytes: int = 2 << 30, emit=None) -> dict:
    """End-to-end ec.rebuild through rebuild_ec_files (ISSUE 3 tentpole):
    reconstruct 4 lost shards (2 data + 2 parity) of a real on-disk shard
    set from its 10 survivors — survivor reads, decode and shard writes all
    included. Two legs over the same shard set, interleaved reps:

    - `ref`: the pre-fast-path structure — synchronous per-chunk loop,
      all-rows codec.reconstruct (pipeline=False, full_reconstruct=True);
    - `best`: the shipping repair fast path — pipelined double-buffered
      reader/decoder/writer, missing-rows-only reconstruct_rows through
      the cached decode matrix, .tmp-then-rename outputs.

    GB/s over SURVIVOR BYTES READ (10 x shard size ~= the original .dat
    bytes — the same basis as the kernel-level rebuild metric and
    ec.encode.e2e's .dat basis, so the numbers are comparable). detail
    carries the best leg's per-stage breakdown (LAST_REBUILD_STAGES:
    read/decode/write; pipelined stages overlap so their sum can exceed
    total). Files live on tmpfs when available, like measure_encode_e2e.
    """
    import shutil
    import tempfile

    from seaweedfs_tpu.storage.erasure_coding import (
        rebuild_ec_files,
        to_ext,
        write_ec_files,
    )
    from seaweedfs_tpu.storage.erasure_coding import encoder as _enc
    from seaweedfs_tpu.tpu.coder import adaptive_codec

    shm_free = (
        shutil.disk_usage("/dev/shm").free if os.path.isdir("/dev/shm") else 0
    )
    if shm_free > (256 << 20) * 3:
        # peak working set: .dat + shard set during encode (2.4x), then
        # shard set + rebuilt tmps during the legs (1.8x)
        size_bytes = min(size_bytes, int(shm_free / 2.6))
        use_dir = "/dev/shm"
    else:
        use_dir = None
        size_bytes = min(size_bytes, 512 << 20)
    size_bytes = max(size_bytes, 64 << 20)
    result = {"size_bytes": size_bytes, "tmpfs": use_dir is not None}

    d = tempfile.mkdtemp(prefix="bench_ec_rebuild_", dir=use_dir)
    try:
        base = os.path.join(d, "1")
        block = np.random.default_rng(7).integers(
            0, 256, size=64 << 20, dtype=np.uint8
        ).tobytes()
        with open(base + ".dat", "wb") as f:
            left = size_bytes
            while left > 0:
                f.write(block[: min(left, len(block))])
                left -= len(block)
        codec = adaptive_codec()
        result["backend"] = type(codec).__name__
        write_ec_files(base, codec=codec)
        os.remove(base + ".dat")  # the legs only need the shard set
        golden = _shard_samples(base)
        shard_size = golden["shard_size"]
        survivor_bytes = 10 * shard_size
        result["shard_size"] = shard_size
        missing = [0, 1, 11, 13]
        result["missing"] = missing

        def kill() -> None:
            for i in missing:
                os.remove(base + to_ext(i))

        def run_ref() -> None:
            rebuild_ec_files(
                base, codec=codec, pipeline=False, full_reconstruct=True
            )

        def run_best() -> None:
            rebuild_ec_files(base, codec=codec)
            result["stages"] = {
                k: round(v, 3) for k, v in _enc.LAST_REBUILD_STAGES.items()
            }
            # which structure the measured race picked on this host (the
            # mmap/onepass routes fold the read stage into decode_s)
            result["route"] = dict(_enc.LAST_REBUILD_ROUTE)

        times = {"ref": float("inf"), "best": float("inf")}
        legs = [("ref", run_ref), ("best", run_best)]
        parity_ok = True
        # interleaved alternating order: same credit-throttle fairness
        # argument as measure_encode_e2e
        for rep in range(4):
            order = legs if rep % 2 == 0 else legs[::-1]
            for name, fn in order:
                kill()
                t0 = time.perf_counter()
                fn()
                times[name] = min(times[name], time.perf_counter() - t0)
                if times["ref"] != float("inf"):
                    result["ref_gbps"] = round(
                        survivor_bytes / times["ref"] / 1e9, 3
                    )
                if times["best"] != float("inf"):
                    result["best_gbps"] = round(
                        survivor_bytes / times["best"] / 1e9, 3
                    )
                if emit:
                    emit(result)
            if rep == 0:
                # rebuilt set must hash-match the originally encoded one
                parity_ok = parity_ok and (_shard_samples(base) == golden)
                result["rebuilt_byte_identical"] = parity_ok
        result["rebuilt_byte_identical"] = parity_ok and (
            _shard_samples(base) == golden
        )
        from seaweedfs_tpu.util import available_cpus

        result["host_cpus"] = available_cpus()
        return result
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_degraded_read(size_bytes: int = 64 << 20) -> dict:
    """Degraded-read latency attribution (ISSUE 3): the in-process cost of
    serving one 4KB interval of a dead shard (a) cold — survivor reads of
    the 128KiB readahead span + missing-row-only decode + span cache fill,
    (b) repeated — served from the degraded-read interval cache. These are
    the floor the server path adds its RPC legs to; the cache-hit leg is
    what every repeat read of a hot dead shard now costs."""
    import shutil
    import tempfile

    from seaweedfs_tpu.server.volume_ec import DegradedIntervalCache
    from seaweedfs_tpu.storage.erasure_coding import to_ext, write_ec_files
    from seaweedfs_tpu.tpu.coder import adaptive_codec

    use_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    d = tempfile.mkdtemp(prefix="bench_ec_degraded_", dir=use_dir)
    try:
        base = os.path.join(d, "1")
        rng = np.random.default_rng(11)
        with open(base + ".dat", "wb") as f:
            f.write(rng.integers(0, 256, size=size_bytes, dtype=np.uint8).tobytes())
        codec = adaptive_codec()
        write_ec_files(base, codec=codec)
        dead = 3
        survivors = [i for i in range(14) if i != dead][:10]
        shard_size = os.path.getsize(base + to_ext(dead))
        files = {i: open(base + to_ext(i), "rb") for i in survivors}
        cache = DegradedIntervalCache()
        iv_size = 4096
        offs = rng.integers(0, max(shard_size - (1 << 17) - iv_size, 1), 24)
        cold_s, hit_s = [], []
        mism = 0
        try:
            with open(base + to_ext(dead), "rb") as truth_f:
                for off in (int(o) for o in offs):
                    t0 = time.perf_counter()
                    span_start, span_size = cache.span_for(
                        off, iv_size, shard_size
                    )
                    slots = [None] * 14
                    for i in survivors:
                        slots[i] = np.frombuffer(
                            os.pread(files[i].fileno(), span_size, span_start),
                            dtype=np.uint8,
                        )
                    row = codec.reconstruct_rows(slots, [dead])[0]
                    span = np.ascontiguousarray(row).tobytes()
                    cache.put(1, dead, span_start, span)
                    got = span[off - span_start : off - span_start + iv_size]
                    cold_s.append(time.perf_counter() - t0)
                    truth_f.seek(off)
                    if got != truth_f.read(iv_size):
                        mism += 1
                    t0 = time.perf_counter()
                    hit = cache.get(1, dead, off, iv_size)
                    hit_s.append(time.perf_counter() - t0)
                    if hit != got:
                        mism += 1
        finally:
            for f in files.values():
                f.close()
        cold_s.sort()
        hit_s.sort()
        cold_ms = cold_s[len(cold_s) // 2] * 1e3
        hit_us = hit_s[len(hit_s) // 2] * 1e6
        return {
            "interval_bytes": iv_size,
            "span_bytes": 1 << 17,
            "cold_p50_ms": round(cold_ms, 3),
            "cache_hit_p50_us": round(hit_us, 1),
            "speedup": round(cold_ms * 1e3 / max(hit_us, 1e-3), 1),
            "mismatches": mism,
            "samples": len(cold_s),
            "backend": type(codec).__name__,
            "tmpfs": use_dir is not None,
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_vacuum_throughput(
    n_needles: int = 12000,
    needle_bytes: int = 4096,
    garbage_every: int = 2,
    reps: int = 3,
) -> dict:
    """Vacuum-plane fast path (ISSUE 5 tentpole): compact a half-garbage
    volume through both structures on the same files, interleaved reps:

    - `naive`: the pre-fast-path reference loop — one needle at a time,
      pread + CRC parse + re-serialize + write (the retained
      `vacuum._copy_naive`, the reference's copyDataBasedOnIndexFile
      structure);
    - `best`: the shipping extent-coalesced path — offset-ordered live
      walk, adjacent records coalesced into multi-MB extents, raw-byte
      moves through the measured-race route (pread ring / mmap views),
      key-sorted .cpx in one vectorized pass.

    GB/s over LIVE BYTES MOVED (the work compaction must do; dead bytes
    cost neither path I/O). detail carries the best leg's stage breakdown
    (LAST_VACUUM_STAGES) and route, plus a content-identity check: every
    live record read back from both shadow sets byte-identical."""
    import shutil
    import tempfile

    from seaweedfs_tpu.storage import vacuum as vacuum_mod
    from seaweedfs_tpu.storage.idx import parse_index_bytes
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.volume import Volume
    from seaweedfs_tpu.types import (
        TOMBSTONE_FILE_SIZE,
        to_actual_offset,
    )

    use_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    d = tempfile.mkdtemp(prefix="bench_vacuum_", dir=use_dir)
    result: dict = {
        "n_needles": n_needles,
        "needle_bytes": needle_bytes,
        "tmpfs": use_dir is not None,
    }
    try:
        v = Volume(d, "", 1)
        rng = np.random.default_rng(17)
        pool = rng.integers(
            0, 256, size=needle_bytes + n_needles, dtype=np.uint8
        ).tobytes()
        for i in range(1, n_needles + 1):
            v.write_needle(
                Needle(id=i, cookie=i, data=pool[i : i + needle_bytes])
            )
        for i in range(1, n_needles + 1, garbage_every):
            v.delete_needle(Needle(id=i, cookie=i))
        v.sync()
        base = v.file_name()
        sb, version = v.super_block, v.version
        v.close()
        result["garbage_ratio"] = round(
            1 - 1 / garbage_every, 3
        )

        shadows = {
            "naive": (base + ".naive.cpd", base + ".naive.cpx"),
            "best": (base + ".cpd", base + ".cpx"),
        }

        def run_naive() -> dict:
            return vacuum_mod._copy_naive(
                base + ".dat", base + ".idx", *shadows["naive"], sb, version
            )

        def run_best() -> dict:
            r = vacuum_mod._copy_data_based_on_index_file(
                base + ".dat", base + ".idx", *shadows["best"], sb, version
            )
            result["stages"] = {
                k: round(x, 4)
                for k, x in vacuum_mod.LAST_VACUUM_STAGES.items()
            }
            result["route"] = dict(vacuum_mod.LAST_VACUUM_ROUTE)
            return r

        times = {"naive": float("inf"), "best": float("inf")}
        legs = [("naive", run_naive), ("best", run_best)]
        live_bytes = 0
        for rep in range(reps):
            order = legs if rep % 2 == 0 else legs[::-1]
            for name, fn in order:
                t0 = time.perf_counter()
                r = fn()
                times[name] = min(times[name], time.perf_counter() - t0)
                live_bytes = max(live_bytes, int(r.get("live_bytes", 0)))
        result["live_bytes"] = live_bytes
        result["naive_gbps"] = round(live_bytes / times["naive"] / 1e9, 4)
        result["best_gbps"] = round(live_bytes / times["best"] / 1e9, 4)
        result["vs_naive"] = round(times["naive"] / times["best"], 2)

        # content identity: every live record byte-identical across the
        # two shadow sets (layouts differ by design: key vs offset order)
        def blob_map(cpd: str, cpx: str) -> dict:
            with open(cpx, "rb") as f:
                keys, offs, sizes = parse_index_bytes(f.read())
            out = {}
            with open(cpd, "rb") as f:
                for k, off, size in zip(
                    keys.tolist(), offs.tolist(), sizes.tolist()
                ):
                    if off == 0 or size == TOMBSTONE_FILE_SIZE:
                        continue
                    from seaweedfs_tpu.storage.needle import get_actual_size

                    f.seek(to_actual_offset(off))
                    out[k] = f.read(get_actual_size(size, version))
            return out

        result["identical"] = blob_map(*shadows["naive"]) == blob_map(
            *shadows["best"]
        )
        return result
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _shard_samples(base: str, rng_seed: int = 1) -> dict:
    """Sizes + sampled 1MB-block hashes of a shard set (then the caller can
    delete the files, keeping only one set on disk at a time)."""
    import hashlib

    from seaweedfs_tpu.storage.erasure_coding import to_ext

    rng = np.random.default_rng(rng_seed)
    shard_size = os.path.getsize(base + to_ext(0))
    offs = rng.integers(0, max(shard_size - (1 << 20), 1), 8)
    out = {"shard_size": shard_size, "hashes": []}
    for i in range(14):
        if os.path.getsize(base + to_ext(i)) != shard_size:
            out["hashes"].append(None)
            continue
        h = []
        with open(base + to_ext(i), "rb") as f:
            for off in offs:
                f.seek(int(off))
                h.append(hashlib.md5(f.read(1 << 20)).hexdigest())
        out["hashes"].append(h)
    return out


def _rm_shards(base: str) -> None:
    from seaweedfs_tpu.storage.erasure_coding import to_ext

    for i in range(14):
        try:
            os.remove(base + to_ext(i))
        except OSError:
            pass


def _measure_io_legs(d: str, base: str, sample: int = 512 << 20) -> dict:
    """Per-leg file-IO unit costs on the e2e working directory, measured
    back-to-back with the pipelines so throttle state matches: sequential
    read of the existing .dat (readinto, preallocated buffer) and a
    fresh-file write (page allocation + copy — the cost every new shard
    file pays). -> {read_gbps, fresh_write_gbps}; the route-dependent
    ceilings are assembled in _e2e_results where the executed route is
    known."""
    sample = min(sample, os.path.getsize(base + ".dat"))
    buf = bytearray(64 << 20)
    mv = memoryview(buf)
    t0 = time.perf_counter()
    got = 0
    with open(base + ".dat", "rb", buffering=0) as f:
        while got < sample:
            n = f.readinto(mv[: min(len(buf), sample - got)])
            if not n:
                break
            got += n
    read_gbps = got / (time.perf_counter() - t0) / 1e9

    scratch = os.path.join(d, "_io_leg_scratch")
    block = bytes(buf)
    t0 = time.perf_counter()
    written = 0
    with open(scratch, "wb") as f:
        while written < sample:
            n = f.write(block[: min(len(block), sample - written)])
            written += n
    write_gbps = written / (time.perf_counter() - t0) / 1e9
    os.remove(scratch)

    return {
        "read_gbps": round(read_gbps, 2),
        "fresh_write_gbps": round(write_gbps, 2),
    }


def measure_encode_e2e(size_bytes: int = 4 << 30, emit=None):
    """End-to-end `ec.encode` of one .dat through write_ec_files: disk reads,
    host packing, encode and shard writes included (BASELINE.json config 1;
    ref ec_encoder.go:120-136). Three pipelines over the same .dat:

    - `ref`: the reference's structure — single-threaded, synchronous, 256KB
      buffer (ec_encoder.go:57-58,120-136) — over the native SIMD codec (the
      klauspost-equivalent). This is the baseline to beat.
    - `tpu`: the device pipeline (upload/kernel/download overlapped with file
      IO). NOTE: on the tunneled bench backend host<->device moves at
      ~0.5 GB/s up / ~0.03 GB/s down, so this leg is transfer-bound; on a
      directly-attached chip the same code is IO-bound instead.
    - `best`: the shipping adaptive route (tpu/coder.adaptive_codec) with the
      pipelined multi-worker structure — large chunks, zero-copy writes,
      encode parallelized across cores while the main thread streams IO.

    Returns a dict; `emit`, when given, receives each leg's partial dict as
    it completes so a timeboxed parent keeps whatever finished. Files live on
    tmpfs when available (this VM's block device is writeback-throttled to
    ~30-80MB/s, which would turn every pipeline into a disk benchmark) and
    the working set is capped to fit: .dat + one shard set at a time.
    """
    import shutil
    import tempfile

    from seaweedfs_tpu.storage.erasure_coding import write_ec_files
    from seaweedfs_tpu.tpu.coder import adaptive_codec, get_codec

    shm_free = (
        shutil.disk_usage("/dev/shm").free if os.path.isdir("/dev/shm") else 0
    )
    if shm_free > (256 << 20) * 3:
        size_bytes = min(size_bytes, int(shm_free // 3))
        use_dir = "/dev/shm"
    else:
        use_dir = None  # block device; honest but throttled — note carries
        # it. Writeback-throttled disks run ~0.1 GB/s; keep the interleaved
        # rep loop inside the timebox
        size_bytes = min(size_bytes, 1 << 30)
    size_bytes = max(size_bytes, 64 << 20)
    result = {"size_bytes": size_bytes, "tmpfs": use_dir is not None}

    d = tempfile.mkdtemp(prefix="bench_ec_e2e_", dir=use_dir)
    try:
        base = os.path.join(d, "1")
        # 64MB of randomness repeated: content doesn't affect GF throughput
        block = np.random.default_rng(0).integers(
            0, 256, size=64 << 20, dtype=np.uint8
        ).tobytes()
        with open(base + ".dat", "wb") as f:
            left = size_bytes
            while left > 0:
                f.write(block[: min(left, len(block))])
                left -= len(block)

        # --- reference-style baseline vs best (shipping adaptive) path,
        # timed as ALTERNATING interleaved reps: on credit-throttled VMs
        # whichever leg runs first gets the spare burst credits, so a
        # run-all-of-A-then-all-of-B structure biases the ratio ---
        cpu_codec = get_codec("cpu")
        best = adaptive_codec()
        result["best_backend"] = {
            "TpuRSCodec": "tpu",
            "NativeRSCodec": "cpu-native",
            "CpuRSCodec": "cpu-numpy",
        }.get(type(best).__name__, type(best).__name__)

        def run_ref():
            write_ec_files(
                base, codec=cpu_codec, chunk=256 * 1024,
                pipeline=False, splice_data=False, mmap_input=False,
            )

        def run_best():
            from seaweedfs_tpu.storage.erasure_coding import encoder as _enc

            write_ec_files(base, codec=best)
            result["best_route"] = dict(_enc.LAST_ROUTE)
            result["best_stages"] = {
                k: round(v, 3) for k, v in _enc.LAST_STAGES.items()
            }

        golden = None
        best_samples = None
        times = {"ref": float("inf"), "best": float("inf")}
        legs = [("ref", run_ref), ("best", run_best)]
        for rep in range(4):
            order = legs if rep % 2 == 0 else legs[::-1]
            for name, fn in order:
                _rm_shards(base)
                t0 = time.perf_counter()
                fn()
                times[name] = min(times[name], time.perf_counter() - t0)
                if name == "ref" and golden is None:
                    golden = _shard_samples(base)
                if name == "best" and best_samples is None:
                    best_samples = _shard_samples(base)
                # partials after EVERY leg: a timebox kill even during
                # rep 0's second leg still leaves the first leg's number
                if times["ref"] != float("inf"):
                    result["ref_gbps"] = size_bytes / times["ref"] / 1e9
                if times["best"] != float("inf"):
                    result["best_gbps"] = size_bytes / times["best"] / 1e9
                    result["best_parity"] = best_samples == golden
                if emit:
                    emit(result)
        _rm_shards(base)
        try:
            # bandwidth context for the ratio (formatted by _e2e_results);
            # measured here so it stays inside the e2e timebox accounting
            result["host_memcpy_gbps"] = round(measure_memcpy_roofline(), 2)
        except Exception:
            pass
        try:
            # the REAL e2e roofline (VERDICT r4 item 8): file IO on this
            # host is 2-4x slower than memcpy (fresh tmpfs writes fault +
            # zero pages; reads allocate), so the honest ceiling is built
            # from measured file-leg unit costs IN THE SAME THROTTLE
            # WINDOW: read the source once, write 1.4 bytes of shards
            result["io_legs"] = _measure_io_legs(d, base)
        except Exception:
            pass
        if emit:
            # the device leg below can die to a slow tunnel; the roofline
            # and memcpy context must already be in the last partial
            emit(result)

        # --- device pipeline (always measured, even when transfer-bound;
        # smaller cap so a slow tunnel can't eat the whole timebox) ---
        tpu_size = min(size_bytes, 1 << 30)
        if tpu_size != size_bytes:
            os.truncate(base + ".dat", tpu_size)
            golden = None  # parity sampled against a fresh ref run below
        tpu_codec = get_codec("tpu")
        # warm the dispatch the streamed pipeline actually runs (device
        # kernel, or the substituted host kernel on the CPU stand-in) so
        # first-call jit/table setup stays out of the timed window
        warm = getattr(tpu_codec, "pipeline_encode", tpu_codec.encode)
        warm(np.zeros((10, tpu_codec.preferred_chunk), np.uint8))
        from seaweedfs_tpu.storage.erasure_coding import encoder as _enc

        t0 = time.perf_counter()
        write_ec_files(base, codec=tpu_codec)
        result["tpu_gbps"] = tpu_size / (time.perf_counter() - t0) / 1e9
        result["tpu_size_bytes"] = tpu_size
        result["tpu_stages"] = {
            k: round(v, 3) for k, v in _enc.LAST_STAGES.items()
        }
        result["tpu_route"] = dict(_enc.LAST_ROUTE)
        result["device_status"] = _device_status()
        tpu_samples = _shard_samples(base)
        _rm_shards(base)
        if golden is None:
            write_ec_files(
                base, codec=cpu_codec, chunk=256 * 1024,
                pipeline=False, splice_data=False, mmap_input=False,
            )
            golden = _shard_samples(base)
            _rm_shards(base)
        result["tpu_parity"] = tpu_samples == golden
        if emit:
            emit(result)
        return result
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_geometries() -> dict:
    """Kernel encode throughput at the alternate RS geometries
    (BASELINE.json config 5: 6.3 / 12.4 alongside the default 10.4)."""
    from seaweedfs_tpu.ops.gf256 import pack_bytes_host
    from seaweedfs_tpu.storage.erasure_coding.galois import build_matrix

    rng = np.random.default_rng(9)
    out = {}
    for k, m in ((6, 3), (12, 4)):
        matrix = build_matrix(k, k + m)[k:]
        data = rng.integers(0, 256, size=(k, 8 << 20), dtype=np.uint8)
        out[f"{k}.{m}"] = round(
            measure_tpu(matrix, pack_bytes_host(data)), 3
        )
    return out


def measure_multi_encode(
    n_volumes: int = 8, vol_bytes: int = 32 << 20
) -> dict:
    """Aggregate GB/s of encoding `n_volumes` concurrently through
    write_ec_files_multi vs the same volumes sequentially through the
    single-volume pipeline, same (adaptive) codec — BASELINE.json config 3.
    Device codecs stream shared wide batches; host codecs run volumes across
    cores. Steady-state: best of 2 runs each."""
    import shutil
    import tempfile

    from seaweedfs_tpu.storage.erasure_coding import (
        write_ec_files,
        write_ec_files_multi,
    )
    from seaweedfs_tpu.tpu.coder import adaptive_codec

    shm_ok = (
        os.path.isdir("/dev/shm")
        and shutil.disk_usage("/dev/shm").free > 4 * n_volumes * vol_bytes
    )
    d = tempfile.mkdtemp(
        prefix="bench_multi_", dir="/dev/shm" if shm_ok else None
    )
    total = n_volumes * vol_bytes
    try:
        block = np.random.default_rng(2).integers(
            0, 256, size=min(vol_bytes, 64 << 20), dtype=np.uint8
        ).tobytes()
        bases = []
        for v in range(n_volumes):
            os.makedirs(os.path.join(d, str(v)))
            base = os.path.join(d, str(v), "1")
            with open(base + ".dat", "wb") as f:
                left = vol_bytes
                while left > 0:
                    f.write(block[: min(left, len(block))])
                    left -= len(block)
            bases.append(base)

        codec = adaptive_codec()

        def run_seq() -> None:
            for base in bases:
                write_ec_files(base, codec=codec)

        def run_multi() -> None:
            write_ec_files_multi(bases, codec=codec)

        from seaweedfs_tpu.util import available_cpus

        out = {
            "n_volumes": n_volumes,
            "vol_bytes": vol_bytes,
            "tmpfs": shm_ok,
            "backend": type(codec).__name__,
            # concurrency can only beat the sequential leg with >1 core:
            # the host codec releases the GIL, but parallel sections still
            # need somewhere to run (BENCH hosts to date expose 1 CPU,
            # which is why multi/seq has pinned at ~1.0x)
            "host_cpus": available_cpus(),
        }
        # interleaved best-of-4 with ALTERNATING order: on credit-throttled
        # VMs whichever leg runs first in a rep gets the spare burst
        # credits, a systematic bias that a fixed order bakes into the
        # ratio; alternation gives each leg equal first-position runs
        best = {"seq_gbps": float("inf"), "multi_gbps": float("inf")}
        legs = [("seq_gbps", run_seq), ("multi_gbps", run_multi)]
        for rep in range(4):
            order = legs if rep % 2 == 0 else legs[::-1]
            for name, fn in order:
                t0 = time.perf_counter()
                fn()
                best[name] = min(best[name], time.perf_counter() - t0)
        for name, t in best.items():
            out[name] = total / t / 1e9
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _write_legs_us(stats_out: dict) -> Optional[dict]:
    """run_benchmark's write-leg Stats -> flat microsecond dict (avg
    carries the sub-0.1ms resolution the 0.1ms-bucket p50 can't)."""
    wlegs = stats_out.get("write_legs")
    if not wlegs:
        return None

    def leg(stats) -> tuple[float, float]:
        avg = stats._sum_ms / max(stats.completed, 1) * 1000.0
        return round(avg, 1), round(stats.percentile(50) * 1000, 1)

    a_avg, a_p50 = leg(wlegs["assign_stats"])
    b_avg, b_p50 = leg(wlegs["build_stats"])
    u_avg, u_p50 = leg(wlegs["upload_stats"])
    return {
        "assign_avg_us": a_avg,
        "assign_p50_us": a_p50,
        "build_avg_us": b_avg,
        "build_p50_us": b_p50,
        "upload_avg_us": u_avg,
        "upload_p50_us": u_p50,
        "assign_rpcs": wlegs["assign_rpcs"],
        "assign_batch": wlegs["assign_batch"],
    }


def _free_port_pair() -> int:
    """A port p with both p and p+10000 free (HTTP + gRPC listener pair),
    shared by the in-process-cluster serving legs."""
    import socket

    for p in range(18200, 19200):
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", p))
            with socket.socket() as s:
                s.bind(("127.0.0.1", p + 10000))
            return p
        except OSError:
            continue
    raise RuntimeError("no free port pair")


def measure_serving_qps(
    num_files: int = 3000, concurrency: int = 16
) -> dict:
    """Write + random-read QPS of 1KB files through the full HTTP serving
    stack — in-process master + volume server on tmpfs, the `weed benchmark`
    workload (BASELINE.json config 4; reference numbers: 15,708 write /
    47,019 read #/sec, ref README.md:483-530).

    Reads are measured twice: per-request index lookups (the reference's
    structure), then with the BatchLookupGate micro-batching concurrent
    probes through one vectorized bulk_lookup per tick (north-star #2's
    serving path; `-batchLookup` on the CLI). Set BENCH_QPS_DEVICE=1 to
    force the gate's batches onto the device kernel as a third leg
    (meaningful on directly-attached chips; over the bench tunnel the
    per-batch RTT dominates and the auto policy correctly serves from the
    host snapshot instead)."""
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_qps_", dir="/dev/shm" if os.path.isdir("/dev/shm") else None
    )
    out: dict = {"num_files": num_files, "concurrency": concurrency}
    free_port_pair = _free_port_pair

    async def body() -> None:
        from seaweedfs_tpu.command.benchmark import run_benchmark
        from seaweedfs_tpu.pb.rpc import close_all_channels
        from seaweedfs_tpu.server.lookup_gate import BatchLookupGate
        from seaweedfs_tpu.server.master import MasterServer
        from seaweedfs_tpu.server.volume import VolumeServer

        ms = MasterServer(port=free_port_pair(), pulse_seconds=0.2)
        await ms.start()
        vs = VolumeServer(
            master=ms.address,
            directories=[d],
            port=free_port_pair(),
            pulse_seconds=0.2,
            max_volume_counts=[20],
        )
        await vs.start()
        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)

            def pcts(stats) -> dict:
                if stats is None:
                    return {}
                return {
                    "min_ms": round(stats.latencies_ns_min / 1e6, 2),
                    "avg_ms": round(
                        stats._sum_ms / max(stats.completed, 1), 2
                    ),
                    "max_ms": round(stats.latencies_ns_max / 1e6, 2),
                    "p50_ms": stats.percentile(50),
                    "p95_ms": stats.percentile(95),
                    "p99_ms": stats.percentile(99),
                }

            # write once + plain read at c=16 (reference benchmark shape);
            # assigns ride a count=128 lease (the reference benchmark's
            # fid-reuse trick) so the master round-trip is amortized to
            # 1/128 of a write
            s1: dict = {}
            await run_benchmark(
                ms.address, num_files=num_files, file_size=1024,
                concurrency=concurrency, stats_out=s1, assign_batch=128,
            )
            out["write_qps"] = round(s1.get("write_qps", 0))
            out["read_qps"] = round(s1.get("read_qps", 0))
            out["failed"] = s1.get("write_failed", 0) + s1.get("read_failed", 0)
            out["write_latency"] = pcts(s1.get("write_stats"))
            out["read_latency"] = pcts(s1.get("read_stats"))
            # early + final write sub-samples (VERDICT §7: the host's
            # ~30% swing must be disclosed next to the official number)
            out["write_samples"] = s1.get("write_samples")
            wl = _write_legs_us(s1)
            if wl:
                out["write_legs"] = wl
            fids = s1.get("fids") or []

            async def read_leg(conc: int, gate, nf: int = 0) -> dict:
                vs.lookup_gate = gate
                s: dict = {}
                await run_benchmark(
                    ms.address, num_files=nf or num_files, file_size=1024,
                    concurrency=conc, stats_out=s, do_write=False,
                    fids_in=fids,
                )
                return s

            # batched vs plain at both c=16 and c=64 (VERDICT r3 #3: the
            # gate must win at both, and both legs must be recorded).
            # Alternating rounds, best-of per leg: this VM's burst-credit
            # throttling penalizes whichever leg happens to run later, so a
            # single-pass A-then-B ordering biases the comparison (same
            # guard the e2e encode bench uses).
            legs = {
                "read_qps": (concurrency, False),
                "read_qps_batched": (concurrency, True),
                "read_qps_c64": (64, False),
                "read_qps_batched_c64": (64, True),
            }
            # seed every leg so an all-failures run records zeros instead
            # of KeyError-ing away the whole serving entry
            best: dict = {name: (-1, {}) for name in legs}
            samples: dict = {name: [] for name in legs}
            names = list(legs)
            for rnd in range(3):
                order = names if rnd % 2 == 0 else names[::-1]
                for name in order:
                    conc, gated = legs[name]
                    gate = (
                        BatchLookupGate(vs.store, use_device=False)
                        if gated
                        else None
                    )
                    s = await read_leg(conc, gate)
                    samples[name].append(round(s.get("read_qps", 0)))
                    if s.get("read_qps", 0) > best[name][0]:
                        best[name] = (s.get("read_qps", 0), s)
                    if gated:
                        out[
                            "largest_batch"
                            if conc == concurrency
                            else "largest_batch_c64"
                        ] = vs.lookup_gate.stats["largest_batch"]
            for name, (qps, s) in best.items():
                out[name] = round(max(qps, 0))
            # per-round samples with min/max disclosed: the official
            # number is the best round, and these show the swing it rode
            out["read_samples"] = {
                name: {
                    "rounds": vals,
                    "min": min(vals) if vals else 0,
                    "max": max(vals) if vals else 0,
                }
                for name, vals in samples.items()
            }
            out["read_qps"] = round(
                max(best["read_qps"][0], s1.get("read_qps", 0))
            )
            out["batched_failed"] = best["read_qps_batched"][1].get(
                "read_failed", 0
            )
            out["read_latency_batched"] = pcts(
                best["read_qps_batched"][1].get("read_stats")
            )

            # device-gate leg (VERDICT r3 #3 asked for it in the artifact;
            # on the tunneled bench backend per-batch RTT dominates, which
            # the number honestly records). Self-invalidating: the leg
            # carries valid=False whenever the device is a CPU stand-in.
            if os.environ.get("BENCH_QPS_DEVICE", "1") != "0":
                try:
                    s3 = await asyncio.wait_for(
                        read_leg(
                            concurrency,
                            BatchLookupGate(vs.store, use_device=True),
                            nf=200,  # fixed small sample: each batch pays
                            # the tunnel RTT, so the leg records tunnel
                            # latency honestly without eating the budget
                        ),
                        timeout=60,
                    )
                    out["read_qps_batched_device"] = round(
                        s3.get("read_qps", 0)
                    )
                    out["read_qps_batched_device_valid"] = (
                        _device_status() == "tpu"
                    )
                except asyncio.TimeoutError:
                    out["read_qps_batched_device_error"] = (
                        "timeboxed out (device RTT-bound)"
                    )
                except Exception as e:
                    out["read_qps_batched_device_error"] = str(e)[:120]
            # the adaptive gate's own host-vs-device routing decision for
            # this environment (Volume.bulk_lookup's auto policy), stated
            # in the artifact so a stand-in run can't masquerade as a
            # device-served one (VERDICT §4)
            try:
                from seaweedfs_tpu.storage.volume import _device_available
                from seaweedfs_tpu.types import OFFSET_SIZE

                dev_ok = bool(_device_available()) and OFFSET_SIZE == 4
                out["lookup_gate_decision"] = {
                    "auto_routes_to": "device" if dev_ok else "host",
                    "device_status": _device_status(),
                    "valid_as_device_number": _device_status() == "tpu",
                }
            except Exception as e:
                out["lookup_gate_decision"] = {"error": str(e)[:120]}
            vs.lookup_gate = None
        finally:
            await vs.stop()
            await ms.stop()
            await close_all_channels()

    try:
        asyncio.run(body())
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out


def measure_serving_open_loop(
    num_files: int = 20000,
    zipf_s: float = 1.1,
    cold_fraction: float = 0.05,
    rate: Optional[float] = None,
    duration: float = 6.0,
    ping: Optional[dict] = None,
    brownout_leg: bool = True,
    write_concurrency: int = 16,
) -> dict:
    """Open-loop zipfian read leg (ISSUE 6 tentpole): the serving read
    plane measured the way production load actually arrives.

    The closed-loop `serving_read_qps` leg is c clients in lock-step with
    uniform keys — it cannot exhibit coordinated omission (a stalled
    server stops being offered load) and it defeats any popularity-based
    cache by construction. This leg instead:

    - writes a corpus whose sizes draw from a weighted mix (mostly 1KB);
    - offers GETs at a FIXED Poisson arrival rate (default: the measured
      `serving_ping_ceiling` — the stack's own trivial-200 throughput),
      latency-unbounded, keys zipf(`zipf_s`)-popular with a uniform cold
      fraction;
    - records latency from each request's SCHEDULED arrival in a
      log-bucketed histogram, so p50/p99/p999 include the queueing delay
      a backlogged server causes (the coordinated-omission correction);
    - reads ride the client replica fan-out (round-robin + p99 hedging);
    - the volume server's hot-needle cache absorbs the skew: hit rate,
      entries and the byte-identity check (cached vs uncached reads of
      the same fids) are all in the detail;
    - an optional short brownout sub-leg (util/faults.brownout: ramped
      latency on the HTTP client seam) shows the tail metrics responding
      to a degrading path — the reason p999 is published at all.
    """
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_ol_", dir="/dev/shm" if os.path.isdir("/dev/shm") else None
    )
    offered = float(rate or (ping or {}).get("ping_qps") or 20000.0)
    out: dict = {
        "num_files": num_files,
        "zipf_s": zipf_s,
        "cold_fraction": cold_fraction,
        "offered_qps": round(offered),
        "duration_s": duration,
    }
    free_port_pair = _free_port_pair

    async def body() -> None:
        from seaweedfs_tpu.client import MasterClient
        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.client.read_fanout import ReplicaReader
        from seaweedfs_tpu.ops.loadgen import (
            SizeDist,
            ZipfKeys,
            arrival_count,
            run_open_loop,
        )
        from seaweedfs_tpu.pb.rpc import close_all_channels
        from seaweedfs_tpu.server.master import MasterServer
        from seaweedfs_tpu.server.volume import VolumeServer
        from seaweedfs_tpu.util import faults
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        ms = MasterServer(port=free_port_pair(), pulse_seconds=0.2)
        await ms.start()
        vs = VolumeServer(
            master=ms.address,
            directories=[d],
            port=free_port_pair(),
            pulse_seconds=0.2,
            max_volume_counts=[20],
        )
        await vs.start()
        mc = MasterClient("bench-open-loop", [ms.address])
        await mc.start()
        # pool >= open-loop workers: an in-flight count past the pool
        # limit would open-and-discard a TCP connection per excess
        # request, and the churn (~100µs+ each) dominates a saturated leg
        http = FastHTTPClient(pool_per_host=160)
        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)
            await mc.wait_connected()

            # --- corpus: num_files objects, weighted size mix, via the
            # multipart-free zero-copy write tier ---
            sizes = SizeDist(seed=3).draw(num_files)
            out["size_mix_bytes"] = sorted({int(s) for s in sizes.tolist()})

            async def fetch_lease(count: int):
                return await http_assign(http, ms.address, count)

            lease = AssignLease(fetch=fetch_lease, batch=128)
            from seaweedfs_tpu.command.benchmark import fake_payload

            fids: list = []
            widx = [0]

            async def write_worker() -> None:
                from seaweedfs_tpu.util.overload import CircuitOpenError

                while True:
                    i = widx[0]
                    if i >= num_files:
                        return
                    widx[0] = i + 1
                    ar = await lease.take()
                    body = fake_payload(i, int(sizes[i]))
                    # the corpus burst can trip the volume's OWN
                    # admission plane on a loaded host (16 concurrent
                    # writers + event-loop backlog -> write-budget
                    # sheds -> the client breaker opens on the shed
                    # window): honor the 503/breaker like a production
                    # writer instead of dying on the first refusal
                    for _attempt in range(8):
                        try:
                            st, _ = await http.request(
                                "POST", ar.url, "/" + ar.fid,
                                body=body,
                                content_type="application/octet-stream",
                            )
                        except CircuitOpenError:
                            st = 503
                        if st != 503:
                            break
                        await asyncio.sleep(
                            max(
                                0.02,
                                min(
                                    http.retry_after_remaining(ar.url),
                                    1.0,
                                ),
                            )
                        )
                    if st == 201:
                        fids.append(ar.fid)

            t0 = time.perf_counter()
            await asyncio.gather(
                *(write_worker() for _ in range(write_concurrency))
            )
            out["corpus_write_qps"] = round(
                len(fids) / max(time.perf_counter() - t0, 1e-9)
            )
            out["corpus_files"] = len(fids)
            if not fids:
                out["error"] = "corpus write produced no fids"
                return

            # --- open-loop zipfian read leg ---
            zipf = ZipfKeys(
                len(fids), s=zipf_s, seed=11, cold_fraction=cold_fraction
            )
            out["hot_1pct_mass"] = round(zipf.hot_share(0.01), 3)
            reader = ReplicaReader(http, mc.vid_map)
            cache = vs.read_cache

            # the replica reader serves from the MasterClient's vid map,
            # which learns volumes from the 0.2s-pulse KeepConnected
            # stream — wait until every corpus vid has landed, or the
            # first warm read of a just-grown volume LookupErrors the leg
            vids = {int(f.split(",")[0]) for f in fids}
            for _ in range(100):
                if all(mc.vid_map.lookup(v) for v in vids):
                    break
                await asyncio.sleep(0.1)

            # steady-state warm (same discipline as every other leg's
            # compile+warm step): touch every key once so the measured
            # window characterizes the steady-state regime, not an
            # all-miss cold cache. The leg's own hit rate is reported
            # from counters taken AFTER the warm, so whatever the LRU
            # byte bound evicts between warm and use still counts as the
            # misses it really causes.
            warm_q = list(range(len(fids)))
            out["warmed_keys"] = len(warm_q)

            async def warm_worker() -> None:
                while warm_q:
                    k = warm_q.pop()
                    await reader.read_nowait(fids[k])

            await asyncio.gather(*(warm_worker() for _ in range(16)))
            hits0 = cache.hits if cache else 0
            miss0 = cache.misses if cache else 0

            # same-window ping floor: on burst-credit-throttled hosts the
            # standalone serving_ping_ceiling runs in a different credit
            # window than this leg (the corpus writes alone burn seconds
            # of credit), so both the OFFERED rate and the acceptance
            # ratio use a trivial-200 ceiling measured HERE, immediately
            # before the read leg — the same same-throttle-window
            # fairness argument behind the e2e benches' alternating reps.
            # Both pings land in the detail.
            out["inline_ping_qps"] = (
                await _trivial_ping_qps(http, 12000, 16)
            )["ping_qps"]

            offered_leg = float(rate or out["inline_ping_qps"])
            out["offered_qps"] = round(offered_leg)
            keys = zipf.draw(arrival_count(offered_leg, duration)).tolist()

            async def op(i: int) -> bool:
                # read_nowait: single-holder vids get the pooled client's
                # coroutine directly (no extra frame); replicated vids
                # take the round-robin + hedged path
                st, _body = await reader.read_nowait(fids[keys[i]])
                return st == 200

            res = await run_open_loop(
                op, rate=offered_leg, duration=duration, seed=7, workers=64
            )
            out["open_loop"] = res.summary()
            out["achieved_qps"] = out["open_loop"]["achieved_qps"]
            out["read_fanout"] = reader.stats()
            if cache is not None:
                hits, misses = cache.hits - hits0, cache.misses - miss0
                total = max(hits + misses, 1)
                out["cache"] = {
                    **cache.stats(),
                    "leg_hits": hits,
                    "leg_misses": misses,
                    "hit_rate": round(hits / total, 4),
                }
            else:
                out["cache"] = {"disabled": True, "hit_rate": 0.0}

            # --- byte identity: cached hits == uncached reads ---
            ident = True
            sample = fids[:: max(1, len(fids) // 32)][:32]
            for fid in sample:
                st_a, a = await http.request(
                    "GET", vs.address, "/" + fid
                )  # fill (or hit)
                st_b, b = await http.request(
                    "GET", vs.address, "/" + fid
                )  # hit
                if cache is not None:
                    cache.invalidate_volume(
                        int(fid.split(",")[0]), "bench_identity"
                    )
                st_c, c = await http.request(
                    "GET", vs.address, "/" + fid
                )  # uncached
                if not (st_a == st_b == st_c == 200 and a == b == c):
                    ident = False
            out["cached_uncached_identical"] = ident

            # --- brownout sub-leg: ramped latency on the client HTTP
            # seam, tail metrics must move while achieved rate holds ---
            if brownout_leg:
                bo_dur = min(3.0, duration)
                plan = faults.FaultPlan(
                    seed=13,
                    rules=[
                        faults.brownout(
                            op="http:GET",
                            target=f"*:{vs.port}",
                            delay=0.05,
                            start=0.0,
                            duration=bo_dur,
                            probability=0.25,
                        )
                    ],
                )
                bo_rate = offered_leg / 2
                bo_keys = zipf.draw(arrival_count(bo_rate, bo_dur)).tolist()

                async def bo_op(i: int) -> bool:
                    st, _body = await reader.read_nowait(fids[bo_keys[i]])
                    return st == 200

                faults.install_plan(plan)
                try:
                    bo = await run_open_loop(
                        bo_op, rate=bo_rate, duration=bo_dur, seed=17,
                        workers=64,
                    )
                finally:
                    faults.clear_plan()
                out["brownout"] = {
                    **bo.summary(),
                    "injected": plan.fired("http:*"),
                    "peak_delay_ms": 50.0,
                    "probability": 0.25,
                }
        finally:
            await http.close()
            await mc.stop()
            await vs.stop()
            await ms.stop()
            await close_all_channels()

    try:
        asyncio.run(body())
    finally:
        shutil.rmtree(d, ignore_errors=True)
    # acceptance ratio vs the same-credit-window inline ping; the
    # standalone serving_ping_ceiling (different window) is disclosed
    # alongside when the caller passed it
    floor = out.get("inline_ping_qps") or (ping or {}).get("ping_qps")
    if floor:
        out["achieved_over_ping"] = round(
            out.get("achieved_qps", 0) / floor, 3
        )
    if ping and ping.get("ping_qps"):
        out["ceiling_leg_ping_qps"] = ping["ping_qps"]
        out["achieved_over_ceiling_leg"] = round(
            out.get("achieved_qps", 0) / ping["ping_qps"], 3
        )
    return out


def _shed_path_us(iters: int = 50000) -> float:
    """In-situ cost of refusing one request: exactly the work
    `ServingCore._dispatch` does for a shed — classify, `try_admit`
    returning False (deadline), and handing back the pre-rendered 503.
    This is the 'shed responses are served in microseconds' claim
    measured directly, independent of how saturated the bench loop is
    (the client-observed shed RTT in the overload leg rides the same
    backlogged event loop as everything else)."""
    import time as _time

    from seaweedfs_tpu.util import overload

    gate = overload.AdmissionGate("bench-shed", max_queue=4)
    gate.set_read_budget(0.0)  # every arrival has already 'waited past'
    resp = b"x" * 64  # stand-in for the pre-rendered 503 bytes handoff
    classify = overload.classify_method
    _perf = _time.perf_counter
    for _ in range(2000):  # warm
        if gate.try_admit(classify("GET"), 1.0) is False:
            _ = resp
    t0 = _perf()
    for _ in range(iters):
        if gate.try_admit(classify("GET"), 1.0) is False:
            _ = resp
    return (_perf() - t0) / iters * 1e6


def measure_serving_overload(
    num_files: int = 300,
    object_bytes: int = 1 << 20,
    overload_factor: float = 3.0,
    base_duration: float = 2.5,
    duration: float = 4.0,
    recovery_duration: float = 6.0,
    rate: Optional[float] = None,
    workers: int = 64,
) -> dict:
    """serving.overload leg (ISSUE 9): drive the open-loop harness at
    ~`overload_factor`x the measured inline-ping ceiling and show the
    admission plane defending goodput instead of collapsing.

    Unlike every other serving leg, the cluster here runs on its OWN
    thread (own event loop): on a shared loop the load generator
    throttles itself before the server ever backlogs — client-side
    queueing would be measured where server-side shedding is the thing
    under test. With the server on its own loop, offered load past its
    capacity piles up as genuine server-side backlog, the admission
    gate's queue-deadline sees it (the wait between parse and dispatch
    IS the loop backlog), and shedding engages.

    The corpus is `object_bytes` (1MB) objects ON PURPOSE: shedding
    only preserves goodput when serving a request costs much more than
    refusing one. A shed still pays request parse + a pre-rendered 503
    (~the trivial-200 ping cost), so against µs-service traffic (1KB
    cache hits, where service ≈ ping) merely REFUSING a 3x-ping flood
    exceeds the server's whole capacity — no admission policy can hold
    goodput there, and a leg built that way would measure the workload's
    cost ratio, not the control plane. At 1MB the service:shed cost
    ratio is >10x and the 3x-overload equilibrium (goodput ~0.8x + shed
    flood ~0.2x of capacity) exists; the offered rate is therefore
    anchored at `overload_factor`x the measured READ ceiling (the
    'single-rate ceiling' the acceptance compares against), with the
    inline-ping ceiling and offered/ping disclosed alongside.

    Sub-legs, all through one keep-alive client pool:

    - **floors**: cross-thread trivial-200 ping (`_drive_ping` against a
      trivial fast-tier endpoint on the server loop) + a closed-loop
      c=32 read leg whose QPS is the read ceiling R that anchors the
      offered rates and whose p99 scales the gate's read queue budget
      (`AdmissionGate.set_read_budget`: 'waited past its budget' means
      THIS host's numbers);
    - **single-rate ceiling**: the open-loop read leg at 1x R — the
      goodput and admitted-RTT p99 the overloaded run is judged against;
    - **overload**: offered = `overload_factor`x R for `duration`s.
      Discloses goodput (completed 200s/s) vs the ceiling leg, admitted
      RTT p99 vs the ceiling leg's p99, client-observed shed-RTT,
      per-(class,reason) shed counters and the adaptive limit's
      trajectory; the in-situ `shed_path_us` microbench is the µs-shed
      claim measured off the loaded loop;
    - **brownout recovery**: offered 1x for `recovery_duration`s with a
      `util/faults.brownout` on the server seam for the first third;
      per-second goodput buckets show degrade -> heal -> recover.

    Client circuit breakers are DISABLED for this leg (env): the leg
    measures the SERVER admission plane, and an open-loop generator
    that backs off when the peer sheds would be measuring its own
    breaker. Breaker behavior is proven in tests/test_overload.py's
    chaos tests instead."""
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_ov_", dir="/dev/shm" if os.path.isdir("/dev/shm") else None
    )
    out: dict = {
        "num_files": num_files,
        "overload_factor": overload_factor,
        "workers": workers,
    }
    saved_breaker = os.environ.get("SEAWEEDFS_TPU_BREAKER")
    os.environ["SEAWEEDFS_TPU_BREAKER"] = "0"

    # shared threaded fixture (closes the PR 12 round-5 drift: this leg
    # carried its own inline copy of the cluster-thread scaffolding)
    try:
        hold, thread = _start_cluster_thread(
            d, max_volumes=20, with_ping=True
        )
    except RuntimeError as e:
        # the early exit owes the same cleanup the finally below does:
        # a leaked SEAWEEDFS_TPU_BREAKER=0 would silently disable
        # breakers for every LATER bench leg in this process
        out["error"] = str(e)
        if saved_breaker is None:
            os.environ.pop("SEAWEEDFS_TPU_BREAKER", None)
        else:
            os.environ["SEAWEEDFS_TPU_BREAKER"] = saved_breaker
        shutil.rmtree(d, ignore_errors=True)
        return out
    ms, vs = hold["ms"], hold["vs"]
    ping_hostport = f"127.0.0.1:{hold['ping_port']}"

    async def body() -> None:
        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.ops.loadgen import (
            LogHistogram,
            ZipfKeys,
            arrival_count,
            run_open_loop,
        )
        from seaweedfs_tpu.util import faults
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient
        from seaweedfs_tpu.util.metrics import OVERLOAD_SHED

        http = FastHTTPClient(pool_per_host=workers + 16)
        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)

            # --- corpus: object_bytes (1MB) objects via the zero-copy
            # write tier — size is load-bearing, see the docstring ---
            from seaweedfs_tpu.command.benchmark import fake_payload

            async def fetch_lease(count: int):
                return await http_assign(http, ms.address, count)

            lease = AssignLease(fetch=fetch_lease, batch=128)
            fids: list = []
            widx = [0]

            async def write_worker() -> None:
                while True:
                    i = widx[0]
                    if i >= num_files:
                        return
                    widx[0] = i + 1
                    ar = await lease.take()
                    st, _ = await http.request(
                        "POST", ar.url, "/" + ar.fid,
                        body=fake_payload(i, object_bytes),
                        content_type="application/octet-stream",
                    )
                    if st == 201:
                        fids.append(ar.fid)

            await asyncio.gather(*(write_worker() for _ in range(16)))
            out["corpus_files"] = len(fids)
            out["object_bytes"] = object_bytes
            if not fids:
                out["error"] = "corpus write produced no fids"
                return
            # steady-state warm (hot-needle cache filled)
            warm_q = list(range(len(fids)))

            async def warm_worker() -> None:
                while warm_q:
                    k = warm_q.pop()
                    await http.request("GET", vs.address, "/" + fids[k])

            await asyncio.gather(*(warm_worker() for _ in range(16)))

            gate = vs._core.gate
            out["admission_enabled"] = gate is not None

            # cross-thread trivial-200 floor: ~the cost of REFUSING one
            # request, disclosed next to the read ceiling so the
            # service:shed cost ratio this leg depends on is visible
            out["inline_ping_qps"] = (
                await _drive_ping(http, ping_hostport, 12000, 16)
            )["ping_qps"]
            zipf = ZipfKeys(len(fids), s=1.1, seed=11, cold_fraction=0.05)

            # closed-loop read leg: QPS = the read ceiling R anchoring
            # every offered rate below; p99 scales the gate's queue
            # budget
            cl_hist = LogHistogram()
            cl_q = [i % len(fids) for i in range(1200)]
            t0 = time.perf_counter()

            async def cl_worker() -> None:
                while cl_q:
                    k = cl_q.pop()
                    t = time.perf_counter()
                    st, _b = await http.request(
                        "GET", vs.address, "/" + fids[k]
                    )
                    if st == 200:
                        cl_hist.record(time.perf_counter() - t)

            n_cl = len(cl_q)
            await asyncio.gather(*(cl_worker() for _ in range(32)))
            read_ceiling = n_cl / max(time.perf_counter() - t0, 1e-9)
            out["closed_loop_read"] = {
                "qps": round(read_ceiling),
                **cl_hist.summary_ms(),
            }
            ping = float(rate or read_ceiling)
            out["offered_over_ping"] = round(
                ping * overload_factor / max(out["inline_ping_qps"], 1), 3
            )

            def leg_op(keys, ok_hist, shed_hist):
                async def op(i: int) -> bool:
                    t0 = time.perf_counter()
                    st, _body = await http.request(
                        "GET", vs.address, "/" + fids[keys[i]]
                    )
                    dt = time.perf_counter() - t0
                    if st == 200:
                        ok_hist.record(dt)
                        return True
                    if st == 503:
                        shed_hist.record(dt)
                    return False

                return op

            from seaweedfs_tpu.util.overload import latency_percentile

            def admitted_counts() -> list:
                return (
                    list(gate.admitted_counts) if gate is not None else []
                )

            def leg_out(res, ok_hist, shed_hist, shed_delta, adm0) -> dict:
                goodput = res.completed / max(res.duration, 1e-9)
                # server-side admitted latency (admission wait + service,
                # from the gate's log-bucket histogram): the honest
                # "admitted-request p99" — the saturated GENERATOR's own
                # client-side backlog rides the RTT numbers, not these
                adm = [
                    b - a for a, b in zip(adm0, admitted_counts())
                ] or [0]
                return {
                    **res.summary(),
                    "goodput_qps": round(goodput),
                    "admitted_server_p50_ms": round(
                        latency_percentile(adm, 50) * 1e3, 3
                    ),
                    "admitted_server_p99_ms": round(
                        latency_percentile(adm, 99) * 1e3, 3
                    ),
                    "admitted_rtt": ok_hist.summary_ms(),
                    "shed_rtt": shed_hist.summary_ms(),
                    "shed_responses": shed_hist.count,
                    "shed_by_class_reason": {
                        "|".join(f"{k}={v}" for k, v in key): int(n)
                        for key, n in shed_delta.items()
                    },
                }

            def shed_snapshot() -> dict:
                # the server thread inserts first-seen child keys: an
                # unlocked iteration can die mid-leg (dict changed size)
                with OVERLOAD_SHED._lock:
                    return dict(OVERLOAD_SHED._values)

            def shed_since(before: dict) -> dict:
                return {
                    k: v - before.get(k, 0.0)
                    for k, v in shed_snapshot().items()
                    if v - before.get(k, 0.0) > 0
                }

            # --- sub-leg 1: single-rate ceiling (1x R) ---
            shed0, adm0 = shed_snapshot(), admitted_counts()
            base_ok, base_shed = LogHistogram(), LogHistogram()
            keys = zipf.draw(arrival_count(ping, base_duration)).tolist()
            res = await run_open_loop(
                leg_op(keys, base_ok, base_shed),
                rate=ping, duration=base_duration, seed=7, workers=256,
            )
            base_goodput = res.completed / max(res.duration, 1e-9)
            out["ceiling"] = leg_out(
                res, base_ok, base_shed, shed_since(shed0), adm0
            )
            base_p99_s = out["ceiling"]["admitted_server_p99_ms"] / 1e3

            # scale the gate's read queue budget from the ceiling leg's
            # measured SERVER-side admitted p99: 'waited past its
            # budget' now means ~2.5x this host's non-overloaded p99, so
            # admitted p99 <= ~3.5x the ceiling p99 holds by
            # construction and is disclosed as measured (floor 10ms:
            # scheduler jitter must not shed a µs-fast host)
            if gate is not None:
                budget_s = max(0.01, 2.5 * base_p99_s)
                gate.set_read_budget(budget_s)
                out["read_budget_ms"] = round(budget_s * 1e3, 2)

            # --- sub-leg 2: overload at overload_factor x R ---
            shed0, adm0 = shed_snapshot(), admitted_counts()
            limit_before = gate.limiter.limit if gate is not None else None
            ov_ok, ov_shed = LogHistogram(), LogHistogram()
            offered = ping * overload_factor
            keys = zipf.draw(arrival_count(offered, duration)).tolist()
            res = await run_open_loop(
                leg_op(keys, ov_ok, ov_shed),
                rate=offered, duration=duration, seed=17, workers=workers,
            )
            goodput = res.completed / max(res.duration, 1e-9)
            ovl = leg_out(res, ov_ok, ov_shed, shed_since(shed0), adm0)
            out["overload"] = {
                **ovl,
                "limit_before": limit_before,
                "limit_after": (
                    gate.limiter.limit if gate is not None else None
                ),
                "gate": gate.stats() if gate is not None else None,
            }
            # acceptance ratios: goodput holds near the 1x ceiling, the
            # requests that WERE admitted stay bounded (server-side:
            # admission wait + service), sheds are fast
            out["goodput_over_ceiling"] = round(
                goodput / max(base_goodput, 1e-9), 3
            )
            out["admitted_p99_over_ceiling_p99"] = round(
                (ovl["admitted_server_p99_ms"] / 1e3)
                / max(base_p99_s, 1e-9),
                2,
            )
            out["shed_path_us"] = round(_shed_path_us(), 3)

            # --- sub-leg 3: brownout -> heal -> recover ---
            bo_window = recovery_duration / 3.0
            plan = faults.FaultPlan(
                seed=13,
                rules=[
                    faults.brownout(
                        op="http:GET",
                        target=f"*:{vs.port}",
                        delay=0.03,
                        start=0.0,
                        duration=bo_window,
                        probability=0.5,
                    )
                ],
            )
            rc_ok, rc_shed = LogHistogram(), LogHistogram()
            shed0, adm0 = shed_snapshot(), admitted_counts()
            keys = zipf.draw(arrival_count(ping, recovery_duration)).tolist()
            per_second = [0] * (int(recovery_duration) + 8)
            inner = leg_op(keys, rc_ok, rc_shed)
            t_leg0 = time.perf_counter()

            async def rc_op(i: int) -> bool:
                ok = await inner(i)
                if ok:
                    b = int(time.perf_counter() - t_leg0)
                    if b < len(per_second):
                        per_second[b] += 1
                return ok

            faults.install_plan(plan)
            try:
                res = await run_open_loop(
                    rc_op, rate=ping, duration=recovery_duration, seed=23,
                    workers=workers,
                )
            finally:
                faults.clear_plan()
            wall = max(res.duration, 1e-9)
            buckets = per_second[: max(int(wall) + 1, 1)]
            # recovered: post-heal goodput back to >= 0.7x the ceiling.
            # Judged on COMPLETE seconds only — the final bucket covers
            # a partial second (the run ends mid-bucket) and would
            # undercount recovery by whatever fraction it is short
            full = buckets[:-1] if len(buckets) >= 2 else buckets
            tail = full[-2:] if len(full) >= 2 else full
            recovered_qps = sum(tail) / max(len(tail), 1)
            out["brownout_recovery"] = {
                **leg_out(res, rc_ok, rc_shed, shed_since(shed0), adm0),
                "injected": plan.fired("http:*"),
                "brownout_window_s": round(bo_window, 2),
                "goodput_per_second": buckets,
                "recovered_goodput_qps": round(recovered_qps),
                "recovered": bool(recovered_qps >= 0.7 * base_goodput),
            }
        finally:
            await http.close()

    try:
        asyncio.run(body())
    finally:
        _stop_cluster_thread(hold, thread)
        if saved_breaker is None:
            os.environ.pop("SEAWEEDFS_TPU_BREAKER", None)
        else:
            os.environ["SEAWEEDFS_TPU_BREAKER"] = saved_breaker
        shutil.rmtree(d, ignore_errors=True)
    return out



def _start_cluster_thread(
    d: str,
    with_filer_s3: bool = False,
    iam_cfg: Optional[dict] = None,
    chunk_size: int = 64 * 1024,
    max_volumes: int = 50,
    with_ping: bool = False,
):
    """Master + volume (+ filer + S3) on a DEDICATED thread/event loop —
    the serving.overload construction (see measure_serving_overload's
    docstring for why: on a shared loop the generator throttles itself
    before the server backlogs, and server-side admission is the thing
    under test). Returns (hold, thread); hold carries ms/vs (+fs/s3),
    the loop and its stop event; with_ping adds a trivial-200 fast-tier
    endpoint ON the server loop (hold["ping_port"]) — the refuse-one-
    request cost floor the overload leg discloses. Caller MUST
    _stop_cluster_thread."""
    import asyncio
    import threading

    mport = _free_port_pair()
    import socket

    with socket.socket() as _hold:
        _hold.bind(("127.0.0.1", mport))
        vport = _free_port_pair()
        with socket.socket() as _hold2:
            _hold2.bind(("127.0.0.1", vport))
            fport = _free_port_pair() if with_filer_s3 else None
            sport = None
            if with_filer_s3:
                with socket.socket() as _hold3:
                    _hold3.bind(("127.0.0.1", fport))
                    sport = _free_port_pair()
    ready = threading.Event()
    hold: dict = {}

    def server_main() -> None:
        async def run() -> None:
            from seaweedfs_tpu.pb.rpc import close_all_channels
            from seaweedfs_tpu.server.master import MasterServer
            from seaweedfs_tpu.server.volume import VolumeServer

            stop = asyncio.Event()
            hold["stop"] = stop
            hold["loop"] = asyncio.get_event_loop()
            ms = MasterServer(port=mport, pulse_seconds=0.2)
            await ms.start()
            vs = VolumeServer(
                master=ms.address,
                directories=[d],
                port=vport,
                pulse_seconds=0.2,
                max_volume_counts=[max_volumes],
            )
            await vs.start()
            fs = s3 = None
            if with_filer_s3:
                from seaweedfs_tpu.s3.auth import IdentityAccessManagement
                from seaweedfs_tpu.s3.server import S3Server
                from seaweedfs_tpu.server.filer import FilerServer

                fs = FilerServer(
                    master=ms.address, port=fport, chunk_size=chunk_size
                )
                await fs.start()
                iam = (
                    IdentityAccessManagement.from_config(iam_cfg)
                    if iam_cfg
                    else None
                )
                s3 = S3Server(fs, port=sport, iam=iam)
                await s3.start()
            psrv = None
            if with_ping:
                from seaweedfs_tpu.util.fasthttp import (
                    FastHTTPServer,
                    render_response,
                )

                resp = render_response(200, b'{"ok": 1}')

                async def ping_handler(req):
                    return resp

                psrv = FastHTTPServer(ping_handler)
                await psrv.start("127.0.0.1", 0)
                hold["ping_port"] = (
                    psrv._server.sockets[0].getsockname()[1]
                )
            hold["ms"], hold["vs"] = ms, vs
            hold["fs"], hold["s3"] = fs, s3
            ready.set()
            try:
                await stop.wait()
            finally:
                if psrv is not None:
                    await psrv.stop()
                if s3 is not None:
                    await s3.stop()
                if fs is not None:
                    await fs.stop()
                await vs.stop()
                await ms.stop()
                await close_all_channels()

        try:
            asyncio.run(run())
        except Exception as e:
            hold["error"] = repr(e)
            ready.set()

    thread = threading.Thread(target=server_main, daemon=True)
    thread.start()
    if not ready.wait(30) or "error" in hold:
        try:
            if "loop" in hold and "stop" in hold:
                hold["loop"].call_soon_threadsafe(hold["stop"].set)
        except Exception:
            pass
        thread.join(5)
        raise RuntimeError(
            hold.get("error", "server thread failed to start")
        )
    return hold, thread


def _stop_cluster_thread(hold: dict, thread) -> None:
    try:
        hold["loop"].call_soon_threadsafe(hold["stop"].set)
    except Exception:
        pass
    thread.join(30)


def _quota_shed_path_us(iters: int = 50000) -> float:
    """In-situ cost of refusing ONE over-quota request: tenant lookup +
    heat note + dry token-bucket check + pre-bound shed counter — the
    reason=quota twin of `_shed_path_us`. The µs claim of the fairness
    leg: an aggressor's overage costs the server this, not a read."""
    from seaweedfs_tpu.util import overload

    gate = overload.AdmissionGate("bench-quota-shed", max_queue=4)
    gate.set_tenant_quota("aggr", qps=1e-9)  # permanently dry bucket
    classify = overload.classify_method
    cls = classify("GET")
    for _ in range(2000):  # warm
        gate.try_admit(cls, 0.0, "aggr")
    t0 = time.perf_counter()
    for _ in range(iters):
        gate.try_admit(cls, 0.0, "aggr")
    dt = time.perf_counter() - t0
    assert gate.shed_total >= iters
    return dt / iters * 1e6


def measure_qos_fairness(
    num_files: int = 300,
    object_bytes: int = 128 << 10,
    aggr_factor: float = 3.0,
    solo_duration: float = 3.0,
    duration: float = 4.0,
    workers: int = 96,
    util: float = 0.3,
    rate: Optional[float] = None,
) -> dict:
    """qos.fairness leg (ISSUE 12): an aggressive zipf tenant offering
    `aggr_factor`x its fair share runs against a well-behaved tenant,
    and the victim's p99 must stay within a disclosed bound of its
    SOLO-run p99 (acceptance <= 2x) while the aggressor's overage is
    shed with reason=quota at µs cost.

    Construction (the serving.overload scaffolding: server on its own
    thread, client breakers disabled so the generator keeps offering):

    - per-tenant corpora of `object_bytes` (128KB) objects (large
      enough that service cost >> the ~3µs refusal cost — see
      measure_serving_overload's sizing rationale);
    - closed-loop read ceiling R -> fair share = R x util / 2 (two
      tenants, equal weights; `util` is the disclosed provisioning
      headroom — see the inline rationale); the gate's read budget
      scales from the ceiling leg's measured p99 exactly like the
      overload leg;
    - **solo**: victim alone, open-loop at its share -> p99_solo (the
      CO-corrected client RTT — the same construction scores the
      contended run, so the ratio compares like with like);
    - quota: the aggressor gets a rate quota AT its share (weights stay
      equal — the quota is the contract, DRR covers the in-queue
      ordering of whatever is admitted);
    - **contended**: victim at its share and aggressor at
      `aggr_factor`x its share run CONCURRENTLY (two Poisson schedules,
      one loop, one client pool); discloses victim p99 vs solo, per-
      tenant goodput, shed counters by (class, reason, tenant), the
      gate's per-tenant stats, and the in-situ quota-shed µs."""
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_qos_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {
        "num_files": num_files,
        "object_bytes": object_bytes,
        "aggr_factor": aggr_factor,
    }
    saved_breaker = os.environ.get("SEAWEEDFS_TPU_BREAKER")
    os.environ["SEAWEEDFS_TPU_BREAKER"] = "0"
    try:
        hold, thread = _start_cluster_thread(d)
    except RuntimeError as e:
        out["error"] = str(e)
        if saved_breaker is None:
            os.environ.pop("SEAWEEDFS_TPU_BREAKER", None)
        else:
            os.environ["SEAWEEDFS_TPU_BREAKER"] = saved_breaker
        shutil.rmtree(d, ignore_errors=True)
        return out
    ms, vs = hold["ms"], hold["vs"]

    async def body() -> None:
        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.command.benchmark import fake_payload
        from seaweedfs_tpu.ops.loadgen import (
            LogHistogram,
            ZipfKeys,
            arrival_count,
            run_open_loop,
        )
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient
        from seaweedfs_tpu.util.metrics import OVERLOAD_SHED

        http = FastHTTPClient(pool_per_host=workers + 32)
        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)

            async def fetch_lease(count: int):
                return await http_assign(http, ms.address, count)

            lease = AssignLease(fetch=fetch_lease, batch=128)
            fids: dict = {"victim": [], "aggr": []}

            async def write_worker(tenant: str, q: list) -> None:
                while q:
                    i = q.pop()
                    ar = await lease.take()
                    st, _ = await http.request(
                        "POST", ar.url, "/" + ar.fid,
                        body=fake_payload(i, object_bytes),
                        content_type="application/octet-stream",
                        headers={"X-Seaweed-Tenant": tenant},
                    )
                    if st == 201:
                        fids[tenant].append(ar.fid)

            for tenant in ("victim", "aggr"):
                q = list(range(num_files))
                await asyncio.gather(
                    *(write_worker(tenant, q) for _ in range(16))
                )
            out["corpus_files"] = {
                t: len(f) for t, f in fids.items()
            }
            if not fids["victim"] or not fids["aggr"]:
                out["error"] = "corpus write produced no fids"
                return
            # steady-state warm
            for tenant in ("victim", "aggr"):
                warm_q = list(range(len(fids[tenant])))

                async def warm_worker(tenant=tenant, warm_q=warm_q):
                    while warm_q:
                        k = warm_q.pop()
                        await http.request(
                            "GET", vs.address, "/" + fids[tenant][k],
                            headers={"X-Seaweed-Tenant": tenant},
                        )

                await asyncio.gather(*(warm_worker() for _ in range(16)))

            gate = vs._core.gate
            out["admission_enabled"] = gate is not None

            # closed-loop read ceiling R -> fair share = R/2
            cl_hist = LogHistogram()
            cl_q = [i % len(fids["victim"]) for i in range(1000)]
            t0 = time.perf_counter()

            async def cl_worker() -> None:
                while cl_q:
                    k = cl_q.pop()
                    t = time.perf_counter()
                    st, _b = await http.request(
                        "GET", vs.address, "/" + fids["victim"][k],
                        headers={"X-Seaweed-Tenant": "victim"},
                    )
                    if st == 200:
                        cl_hist.record(time.perf_counter() - t)

            n_cl = len(cl_q)
            await asyncio.gather(*(cl_worker() for _ in range(32)))
            ceiling = float(
                rate or (n_cl / max(time.perf_counter() - t0, 1e-9))
            )
            # fair share = half the PROVISIONED capacity: quotas that
            # sum to the raw closed-loop ceiling would run the server at
            # 100% utilization where p99 explodes for everyone and the
            # bound would measure queueing theory, not isolation. The
            # `util` headroom (default 0.3, disclosed as `utilization`)
            # leaves the contended run (total rho = util) room to stay
            # within 2x the solo run (rho = util/2) at the TAIL: the
            # closed-loop ceiling overstates open-loop capacity
            # (pipelining), so effective rho runs above nominal and
            # p99 factors beat the ~1/(1-rho) mean factor — 0.3
            # measures ~1.6x on the dev host, inside the bound with
            # margin where 0.5 measured ~5x
            share = ceiling * util / 2.0
            out["closed_loop_read"] = {
                "qps": round(ceiling), **cl_hist.summary_ms()
            }
            out["utilization"] = util
            out["fair_share_qps"] = round(share)
            if gate is not None:
                budget_s = max(0.01, 2.5 * cl_hist.percentile(99))
                # gate mutations marshal onto the SERVER loop (the
                # soak leg's discipline): set_tenant_quota can trigger
                # _prune_tenants, whose iteration over the tenant
                # table must not race the server thread's inserts
                hold["loop"].call_soon_threadsafe(
                    gate.set_read_budget, budget_s
                )
                out["read_budget_ms"] = round(budget_s * 1e3, 2)

            vic_zipf = ZipfKeys(len(fids["victim"]), s=1.1, seed=5)
            agg_zipf = ZipfKeys(len(fids["aggr"]), s=1.2, seed=9)

            def tenant_op(tenant, keys, ok_hist, shed_hist):
                flist = fids[tenant]
                hdr = {"X-Seaweed-Tenant": tenant}

                async def op(i: int) -> bool:
                    t0 = time.perf_counter()
                    st, _b = await http.request(
                        "GET", vs.address, "/" + flist[keys[i]],
                        headers=hdr,
                    )
                    dt = time.perf_counter() - t0
                    if st == 200:
                        ok_hist.record(dt)
                        return True
                    if st == 503:
                        shed_hist.record(dt)
                    return False

                return op

            def shed_snapshot() -> dict:
                # the server mutates this family on ITS thread: insert
                # of a first-seen (class,reason,tenant) child during an
                # unlocked iteration is a dict-changed-size crash
                with OVERLOAD_SHED._lock:
                    return dict(OVERLOAD_SHED._values)

            def shed_since(before: dict) -> dict:
                return {
                    "|".join(f"{k}={v}" for k, v in key): int(n - before.get(key, 0.0))
                    for key, n in shed_snapshot().items()
                    if n - before.get(key, 0.0) > 0
                }

            from seaweedfs_tpu.util.overload import latency_percentile

            def victim_server_p99(before: list) -> float:
                if gate is None:
                    return 0.0
                now_c = gate.tenant_admitted_counts("victim")
                return latency_percentile(
                    [b - a for a, b in zip(before, now_c)], 99
                )

            # --- solo: the victim alone at its share ---
            adm0 = (
                gate.tenant_admitted_counts("victim")
                if gate is not None
                else []
            )
            vic_solo_ok, vic_solo_shed = LogHistogram(), LogHistogram()
            keys = vic_zipf.draw(
                arrival_count(share, solo_duration)
            ).tolist()
            res = await run_open_loop(
                tenant_op("victim", keys, vic_solo_ok, vic_solo_shed),
                rate=share, duration=solo_duration, seed=31,
                workers=workers,
            )
            out["victim_solo"] = {
                **res.summary(),
                "goodput_qps": round(
                    res.completed / max(res.duration, 1e-9)
                ),
            }
            # the isolation score is SERVER-side (admission wait +
            # service from the gate's per-tenant log buckets): under a
            # saturated shared-loop generator the client RTT records the
            # GENERATOR's backlog — the overload leg's argument, per
            # tenant (RTT percentiles still disclosed alongside)
            p99_solo_s = victim_server_p99(adm0)
            if p99_solo_s <= 0:
                out["error"] = "solo leg recorded no successes"
                return

            # --- quota the aggressor AT its share ---
            if gate is not None:
                import functools

                hold["loop"].call_soon_threadsafe(
                    functools.partial(
                        gate.set_tenant_quota, "aggr", qps=share,
                        burst_s=0.25,
                    )
                )
                await asyncio.sleep(0.05)  # let the install land
                out["aggr_quota_qps"] = round(share)

            # --- contended: victim at share, aggressor at 3x share ---
            shed0 = shed_snapshot()
            adm0 = (
                gate.tenant_admitted_counts("victim")
                if gate is not None
                else []
            )
            vic_ok, vic_shed = LogHistogram(), LogHistogram()
            agg_ok, agg_shed = LogHistogram(), LogHistogram()
            vkeys = vic_zipf.draw(arrival_count(share, duration)).tolist()
            akeys = agg_zipf.draw(
                arrival_count(share * aggr_factor, duration)
            ).tolist()
            vres, ares = await asyncio.gather(
                run_open_loop(
                    tenant_op("victim", vkeys, vic_ok, vic_shed),
                    rate=share, duration=duration, seed=37,
                    workers=workers,
                ),
                run_open_loop(
                    tenant_op("aggr", akeys, agg_ok, agg_shed),
                    rate=share * aggr_factor, duration=duration, seed=41,
                    workers=workers,
                ),
            )
            p99_cont_s = victim_server_p99(adm0)
            out["victim_contended"] = {
                **vres.summary(),
                "goodput_qps": round(
                    vres.completed / max(vres.duration, 1e-9)
                ),
            }
            out["aggressor"] = {
                **ares.summary(),
                "goodput_qps": round(
                    ares.completed / max(ares.duration, 1e-9)
                ),
                "shed_responses": agg_shed.count,
                "shed_rtt": agg_shed.summary_ms(),
            }
            out["victim_p99_solo_ms"] = round(p99_solo_s * 1e3, 3)
            out["victim_p99_contended_ms"] = round(p99_cont_s * 1e3, 3)
            # THE acceptance ratio: victim server-side p99 under attack
            # over its solo run (client RTT blocks disclosed above)
            out["victim_p99_over_solo"] = round(
                p99_cont_s / p99_solo_s, 3
            )
            out["victim_rtt_p99_solo_ms"] = round(
                vic_solo_ok.percentile(99) * 1e3, 3
            )
            out["victim_rtt_p99_contended_ms"] = round(
                vic_ok.percentile(99) * 1e3, 3
            )
            sheds = shed_since(shed0)
            out["shed_by_class_reason_tenant"] = sheds
            out["quota_sheds"] = sum(
                n for k, n in sheds.items() if "reason=quota" in k
            )
            out["quota_shed_path_us"] = round(_quota_shed_path_us(), 3)
            if gate is not None:
                out["gate_tenants"] = gate.tenant_stats()
        finally:
            await http.close()

    try:
        asyncio.run(body())
    except Exception as e:
        out.setdefault("error", f"{type(e).__name__}: {e}")
    finally:
        _stop_cluster_thread(hold, thread)
        if saved_breaker is None:
            os.environ.pop("SEAWEEDFS_TPU_BREAKER", None)
        else:
            os.environ["SEAWEEDFS_TPU_BREAKER"] = saved_breaker
        shutil.rmtree(d, ignore_errors=True)
    return out


def measure_multitenant_soak(
    total_keys: int = 1_000_000,
    tenants: int = 8,
    key_bytes: int = 64,
    s3_fraction: float = 0.01,
    s3_obj_bytes: int = 1024,
    batch: int = 512,
    write_workers: int = 8,
    read_window: float = 4.0,
    read_clients_per_tenant: int = 4,
    fair_limit: int = 8,
    time_cap_s: float = 420.0,
) -> dict:
    """soak.multi_tenant leg (ISSUE 12): drive >= `total_keys` keys
    across `tenants` tenants through the S3 AND raw volume tiers in one
    credit window, disclosing aggregate goodput, a fairness ratio
    (max/min per-tenant goodput under a clamped admission limit so the
    DRR dequeue — not client scheduling — orders the queue), and ZERO
    cross-tenant identity violations: every read performed by the leg
    is byte-compared against that tenant's own deterministic corpus
    (payload = fake_payload(tenant<<56 | index), so any fid/entry
    cross-wiring between tenants is a guaranteed mismatch).

    Raw-tier keys ride the batched fast-tier frame (POST /!batch/put,
    `batch` needles per request — 1M single-needle hops would measure
    HTTP machinery, the soak is about the data plane under identity);
    S3 keys are V4-SIGNED per-tenant PUT/GETs (each tenant its own IAM
    identity + bucket, so the gateway's access-key -> tenant derivation
    is the thing attributing them). If the write phase overruns
    `time_cap_s` the leg STOPS and discloses how many keys it actually
    wrote (no silent caps — `time_capped` says the acceptance target
    was not reached rather than pretending)."""
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_soak_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {
        "target_keys": total_keys,
        "tenants": tenants,
        "key_bytes": key_bytes,
        "s3_obj_bytes": s3_obj_bytes,
    }
    names = [f"tenant{i}" for i in range(tenants)]
    iam_cfg = {
        "identities": [
            {
                "name": n,
                "credentials": [
                    {"accessKey": f"AK{n}", "secretKey": f"SK{n}"}
                ],
                "actions": ["Admin"],
            }
            for n in names
        ]
    }
    saved_breaker = os.environ.get("SEAWEEDFS_TPU_BREAKER")
    os.environ["SEAWEEDFS_TPU_BREAKER"] = "0"
    try:
        hold, thread = _start_cluster_thread(
            d, with_filer_s3=True, iam_cfg=iam_cfg
        )
    except RuntimeError as e:
        out["error"] = str(e)
        if saved_breaker is None:
            os.environ.pop("SEAWEEDFS_TPU_BREAKER", None)
        else:
            os.environ["SEAWEEDFS_TPU_BREAKER"] = saved_breaker
        shutil.rmtree(d, ignore_errors=True)
        return out
    ms, vs, s3 = hold["ms"], hold["vs"], hold["s3"]

    async def body() -> None:
        import struct

        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.command.benchmark import fake_payload
        from seaweedfs_tpu.s3.auth import sign_request
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient
        from seaweedfs_tpu.util.metrics import TENANT_ADMITTED

        http = FastHTTPClient(pool_per_host=64)
        t_leg0 = time.perf_counter()

        def capped() -> bool:
            return time.perf_counter() - t_leg0 > time_cap_s

        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)

            s3_per_tenant = int(total_keys * s3_fraction / tenants)
            raw_per_tenant = (
                total_keys - s3_per_tenant * tenants
            ) // tenants
            out["raw_keys_per_tenant_target"] = raw_per_tenant
            out["s3_keys_per_tenant_target"] = s3_per_tenant

            def payload(tidx: int, i: int, size: int) -> bytes:
                # tenant-disjoint seed space: any cross-tenant mixup is
                # a guaranteed byte mismatch
                return fake_payload((tidx << 56) | i, size)

            async def fetch_lease(count: int):
                # the master sheds assigns while a volume-growth burst
                # blocks its loop: honor the 503 like every other write
                for _ in range(8):
                    try:
                        return await http_assign(http, ms.address, count)
                    except RuntimeError as e:
                        if "503" not in str(e):
                            raise
                        await asyncio.sleep(
                            max(
                                0.05,
                                min(
                                    http.retry_after_remaining(
                                        ms.address
                                    ),
                                    1.0,
                                ),
                            )
                        )
                return await http_assign(http, ms.address, count)

            lease = AssignLease(fetch=fetch_lease, batch=4096)
            fids: list = [[] for _ in range(tenants)]
            violations = [0]
            errors = [0]
            write_sheds = [0]

            async def req_with_retry(method: str, host: str, target: str,
                                     **kw):
                """The soak's writers HONOR the admission plane: a 503
                (the gate shedding under the writers' own burst) sleeps
                out the Retry-After floor and retries — the client
                discipline the overload plane is designed around. Sheds
                are counted and disclosed, not buried as errors."""
                st = resp = None
                for _ in range(8):
                    st, resp = await http.request(
                        method, host, target, **kw
                    )
                    if st != 503:
                        return st, resp
                    write_sheds[0] += 1
                    await asyncio.sleep(
                        max(
                            0.02,
                            min(http.retry_after_remaining(host), 1.0),
                        )
                    )
                return st, resp

            # --- raw-tier write phase: batched frames, tenants
            # interleaved so no tenant's corpus lands "first". The
            # write-class queue budget is WIDENED for the bulk phase
            # (batch frames block the loop for ~batch x append-cost, so
            # the serving-tuned 40ms budget would shed the writers'
            # own backlog constantly) and restored before the latency-
            # scored read window ---
            gate_w = vs._core.gate
            saved_budgets = None
            if gate_w is not None:
                saved_budgets = gate_w.queue_budget_s
                hold["loop"].call_soon_threadsafe(
                    gate_w.set_read_budget, 0.5
                )
            t0 = time.perf_counter()
            work: list = []  # (tidx, start_index) batches
            for tidx in range(tenants):
                i = 0
                while i < raw_per_tenant:
                    n = min(batch, raw_per_tenant - i)
                    work.append((tidx, i, n))
                    i += n
            work.reverse()  # pop() drains in tenant-interleaved order
            stopped = [False]

            async def raw_writer() -> None:
                while work and not stopped[0]:
                    if capped():
                        stopped[0] = True
                        return
                    tidx, start, n = work.pop()
                    items = []
                    for j in range(n):
                        ar = await lease.take()
                        items.append((ar, start + j))
                    parts = [struct.pack("<I", len(items))]
                    for ar, idx in items:
                        fb = ar.fid.encode()
                        body_b = payload(tidx, idx, key_bytes)
                        parts.append(
                            struct.pack("<HI", len(fb), len(body_b))
                        )
                        parts.append(fb)
                        parts.append(body_b)
                    st, resp = await req_with_retry(
                        "POST", vs.address, "/!batch/put",
                        body=b"".join(parts),
                        content_type="application/octet-stream",
                        headers={"X-Seaweed-Tenant": names[tidx]},
                    )
                    if st != 200:
                        errors[0] += n
                        continue
                    import json as _json

                    results = _json.loads(resp)
                    for (ar, idx), r in zip(items, results):
                        if r.get("err"):
                            # single-needle fallback for per-item errors
                            st2, _ = await req_with_retry(
                                "POST", ar.url, "/" + ar.fid,
                                body=payload(tidx, idx, key_bytes),
                                content_type="application/octet-stream",
                                headers={
                                    "X-Seaweed-Tenant": names[tidx]
                                },
                            )
                            if st2 != 201:
                                errors[0] += 1
                                continue
                        fids[tidx].append((ar.fid, idx))

            await asyncio.gather(
                *(raw_writer() for _ in range(write_workers))
            )
            raw_wall = time.perf_counter() - t0
            raw_written = sum(len(f) for f in fids)
            out["raw_keys_written"] = raw_written
            out["raw_write_wall_s"] = round(raw_wall, 2)
            out["raw_write_qps"] = round(raw_written / max(raw_wall, 1e-9))

            # --- S3 write phase: per-tenant buckets, V4-signed PUTs ---
            t0 = time.perf_counter()
            s3_objs: list = [[] for _ in range(tenants)]
            for tidx, n in enumerate(names):
                signed = sign_request(
                    "PUT", f"http://{s3.address}/soak-{n}", {}, b"",
                    f"AK{n}", f"SK{n}",
                )
                hdrs = {
                    k: v for k, v in signed.items()
                    if k.lower() != "host"
                }
                st, _ = await http.request(
                    "PUT", s3.address, f"/soak-{n}", headers=hdrs,
                )
                if st != 200:
                    out["error"] = f"bucket create for {n}: {st}"
                    return
            s3_work = [
                (tidx, i)
                for i in range(s3_per_tenant)
                for tidx in range(tenants)
            ]
            s3_work.reverse()

            async def s3_writer() -> None:
                while s3_work and not stopped[0]:
                    if capped():
                        stopped[0] = True
                        return
                    tidx, i = s3_work.pop()
                    n = names[tidx]
                    body_b = payload(tidx, (1 << 48) | i, s3_obj_bytes)
                    url = f"http://{s3.address}/soak-{n}/k{i:08d}"
                    signed = sign_request(
                        "PUT", url, {}, body_b, f"AK{n}", f"SK{n}"
                    )
                    hdrs = {
                        k: v for k, v in signed.items()
                        if k.lower() != "host"
                    }
                    st, _ = await req_with_retry(
                        "PUT", s3.address, f"/soak-{n}/k{i:08d}",
                        body=body_b,
                        content_type="application/octet-stream",
                        headers=hdrs,
                    )
                    if st == 200:
                        s3_objs[tidx].append(i)
                    else:
                        errors[0] += 1

            await asyncio.gather(
                *(s3_writer() for _ in range(write_workers))
            )
            s3_wall = time.perf_counter() - t0
            s3_written = sum(len(o) for o in s3_objs)
            out["s3_keys_written"] = s3_written
            out["s3_write_wall_s"] = round(s3_wall, 2)
            out["s3_write_qps"] = round(s3_written / max(s3_wall, 1e-9))
            out["keys_written"] = raw_written + s3_written
            if gate_w is not None and saved_budgets is not None:
                hold["loop"].call_soon_threadsafe(
                    setattr, gate_w, "queue_budget_s", saved_budgets
                )
            out["write_errors"] = errors[0]
            out["write_sheds_honored"] = write_sheds[0]
            out["time_capped"] = stopped[0]
            if stopped[0]:
                out["note_cap"] = (
                    f"write phase stopped at time_cap_s={time_cap_s}: "
                    f"{raw_written + s3_written} of {total_keys} keys "
                    "written — acceptance target NOT met this run"
                )

            # --- identity-verified fairness read window: every tenant
            # drives closed-loop raw reads concurrently under a CLAMPED
            # admission limit (inflight > limit -> the DRR queue, not
            # client scheduling, orders service); every read verified
            # byte-identical to the tenant's own corpus ---
            gate = vs._core.gate
            out["admission_enabled"] = gate is not None
            saved_limiter = None
            if gate is not None:
                from seaweedfs_tpu.util.overload import AdaptiveLimiter

                saved_limiter = gate.limiter
                clamped = AdaptiveLimiter(
                    initial=fair_limit, min_limit=fair_limit,
                    max_limit=fair_limit,
                )
                hold["loop"].call_soon_threadsafe(
                    setattr, gate, "limiter", clamped
                )
            rng = np.random.default_rng(77)
            per_tenant_reads = [0] * tenants
            t_read0 = time.perf_counter()

            async def read_worker(tidx: int) -> None:
                flist = fids[tidx]
                if not flist:
                    return
                hdr = {"X-Seaweed-Tenant": names[tidx]}
                idxs = rng.integers(0, len(flist), 4096).tolist()
                pos = 0
                while time.perf_counter() - t_read0 < read_window:
                    fid, idx = flist[idxs[pos % len(idxs)]]
                    pos += 1
                    st, body_b = await http.request(
                        "GET", vs.address, "/" + fid, headers=hdr
                    )
                    if st != 200:
                        continue
                    if body_b != payload(tidx, idx, key_bytes):
                        violations[0] += 1
                    per_tenant_reads[tidx] += 1

            await asyncio.gather(
                *(
                    read_worker(tidx)
                    for tidx in range(tenants)
                    for _ in range(read_clients_per_tenant)
                )
            )
            read_wall = max(time.perf_counter() - t_read0, 1e-9)
            if gate is not None and saved_limiter is not None:
                hold["loop"].call_soon_threadsafe(
                    setattr, gate, "limiter", saved_limiter
                )
            goodputs = [
                r / read_wall for r in per_tenant_reads if r > 0
            ]
            out["read_window_s"] = round(read_wall, 2)
            out["raw_reads_verified"] = sum(per_tenant_reads)
            out["read_goodput_qps"] = round(
                sum(per_tenant_reads) / read_wall
            )
            out["per_tenant_read_qps"] = {
                names[i]: round(per_tenant_reads[i] / read_wall)
                for i in range(tenants)
            }
            out["fairness_ratio"] = (
                round(max(goodputs) / min(goodputs), 3)
                if len(goodputs) == tenants
                else None
            )

            # --- S3 read-back sample: signed GETs, byte-verified ---
            s3_verified = [0]

            async def s3_reader(tidx: int) -> None:
                n = names[tidx]
                sample = s3_objs[tidx][:200]
                for i in sample:
                    url = f"http://{s3.address}/soak-{n}/k{i:08d}"
                    signed = sign_request(
                        "GET", url, {}, b"", f"AK{n}", f"SK{n}"
                    )
                    hdrs = {
                        k: v for k, v in signed.items()
                        if k.lower() != "host"
                    }
                    st, body_b = await http.request(
                        "GET", s3.address, f"/soak-{n}/k{i:08d}",
                        headers=hdrs,
                    )
                    if st != 200:
                        errors[0] += 1
                        continue
                    if body_b != payload(tidx, (1 << 48) | i, s3_obj_bytes):
                        violations[0] += 1
                    s3_verified[0] += 1

            await asyncio.gather(
                *(s3_reader(t) for t in range(tenants))
            )
            out["s3_reads_verified"] = s3_verified[0]
            out["identity_violations"] = violations[0]

            # --- bounded tenant label cardinality, disclosed from the
            # live registry (the tier-1 lint enforces the cap; the leg
            # shows the soak stayed under it) ---
            with TENANT_ADMITTED._lock:  # server thread mutates it
                adm_keys = list(TENANT_ADMITTED._values)
            tenant_labels = {dict(key).get("tenant") for key in adm_keys}
            out["tenant_label_values"] = sorted(
                v for v in tenant_labels if v
            )
            out["tenant_label_cardinality"] = len(tenant_labels)
            if gate is not None:
                out["gate_tenants"] = gate.tenant_stats()
        finally:
            await http.close()

    try:
        asyncio.run(body())
    except Exception as e:
        out.setdefault("error", f"{type(e).__name__}: {e}")
    finally:
        _stop_cluster_thread(hold, thread)
        if saved_breaker is None:
            os.environ.pop("SEAWEEDFS_TPU_BREAKER", None)
        else:
            os.environ["SEAWEEDFS_TPU_BREAKER"] = saved_breaker
        shutil.rmtree(d, ignore_errors=True)
    return out


def measure_production_soak(
    total_keys: int = 10_000_000,
    tenants: int = 16,
    key_bytes: int = 64,
    s3_fraction: float = 0.004,
    s3_obj_bytes: int = 1024,
    batch: int = 512,
    write_workers: int = 8,
    volumes: int = 3,
    filers: int = 2,
    delete_fraction: float = 0.08,
    soak_window_s: float = 20.0,
    offered_fraction: float = 0.5,
    write_mix: float = 0.05,
    fault_count: int = 3,
    seed: int = 31,
    goodput_floor: float = 0.6,
    p99_ceiling_ms: float = 500.0,
    needle_map: str = "lsm",
    needle_map_mb: float = 0.25,
    time_cap_s: float = 600.0,
    quiesce_timeout_s: float = 45.0,
    read_timeout_s: float = 2.0,
) -> dict:
    """soak.production leg (ISSUE 16): ONE sustained, hostile,
    production-shaped proof over a REAL multi-process cluster.

    The cluster is master + `volumes` volume servers + a `filers`-node
    filer fleet + S3 gateway + blob-backend cold tier, every role its
    own OS process (ops/proc_cluster.py) spawned through the `weed-tpu`
    entry points — the first leg where SIGKILL means what it means in
    production. ALL background planes run live via their env gates
    (anti-entropy repair, vacuum, lifecycle incl. cold-tier
    offload/recall against the blob process, scrub budget, orphan
    sweep's reference side), volume servers run the LSM needle map so
    multi-run maps + bloom sidecars appear under sustained load.

    Phases: (1) corpus — >= `total_keys` keys across >= `tenants`
    tenants via batched raw frames + per-tenant V4-signed S3 objects
    (per-tenant BUCKET-SCOPED IAM: Read/Write/List on the tenant's own
    bucket only, so cross-tenant denial is a policy fact the leg can
    probe, not an artifact of Admin-for-everyone); a `delete_fraction`
    slice is deleted to feed the vacuum plane real garbage. (2) chaos
    soak — open-loop zipf traffic (PR 6 CO-corrected percentiles, reads
    + a `write_mix` write stream) at `offered_fraction` x a measured
    closed-loop ceiling, while a SEEDED process-fault schedule
    (util/faults.process_fault_schedule) restarts (SIGKILL + respawn +
    wait-ready) and pauses (SIGSTOP/SIGCONT) volume servers and
    hard-kills one filer, all reproducible bit-for-bit from `seed`
    (disclosed as schedule + schedule_reproducible). (3) quiesce — wait
    out the schedule, then score SLO terms: goodput >= `goodput_floor`
    x offered, foreground CO-corrected p99 <= `p99_ceiling_ms`, ZERO
    byte-identity violations (every verified read byte-compared against
    the tenant's deterministic corpus, including a post-chaos sample
    through the restarted process), ZERO tenant-isolation violations
    (cross-tenant signed GETs must be denied), and every maintenance
    queue (repair/vacuum/lifecycle) drained to depth 0. Bloom-sidecar
    consultation economics are scraped from each live volume process's
    /debug/needle_map and disclosed in the lookup tail."""
    import asyncio
    import shutil
    import struct
    import tempfile

    from seaweedfs_tpu.ops.proc_cluster import ProcCluster, sum_metric
    from seaweedfs_tpu.util.faults import (
        process_fault_schedule,
        process_schedule_to_dicts,
    )

    d = tempfile.mkdtemp(
        prefix="bench_prod_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {
        "target_keys": total_keys,
        "tenants": tenants,
        "volumes": volumes,
        "filers": filers,
        "seed": seed,
        "key_bytes": key_bytes,
    }
    names = [f"tenant{i}" for i in range(tenants)]
    # bucket-scoped IAM: tenant i can touch ONLY soak-tenant{i} — the
    # isolation probe below depends on denial being policy, not luck.
    # PutBucket needs Admin (s3/server.py _required_action), so a
    # separate admin identity does bucket setup and nothing else.
    iam_cfg = {
        "identities": [
            {
                "name": "soakadmin",
                "credentials": [
                    {"accessKey": "AKsoakadmin", "secretKey": "SKsoakadmin"}
                ],
                "actions": ["Admin"],
            }
        ]
        + [
            {
                "name": n,
                "credentials": [
                    {"accessKey": f"AK{n}", "secretKey": f"SK{n}"}
                ],
                "actions": [f"Read:soak-{n}", f"Write:soak-{n}"],
            }
            for n in names
        ]
    }
    child_env = {
        # every background plane LIVE (the gates the threaded legs
        # flip per-plane, all at once):
        "SEAWEEDFS_TPU_AUTO_REPAIR": "1",
        "SEAWEEDFS_TPU_AUTO_VACUUM": "1",
        "SEAWEEDFS_TPU_AUTO_LIFECYCLE": "1",
        "SEAWEEDFS_TPU_SCRUB_MBPS": "20",
        "SEAWEEDFS_TPU_MAINT_MBPS": "200",
        "SEAWEEDFS_TPU_COLD_BACKEND": "s3.default",
        # small memtable so the LSM maps seal real runs (bloom
        # sidecars) within a quick-budget corpus
        "SEAWEEDFS_TPU_NEEDLE_MAP_MB": str(needle_map_mb),
    }
    saved_breaker = os.environ.get("SEAWEEDFS_TPU_BREAKER")
    os.environ["SEAWEEDFS_TPU_BREAKER"] = "0"
    cluster = ProcCluster(
        d,
        volumes=volumes,
        filers=filers,
        with_s3=True,
        with_blob=True,
        iam_cfg=iam_cfg,
        env=child_env,
        needle_map=needle_map,
    )
    try:
        cluster.start()
    except Exception as e:
        out["error"] = f"cluster start: {type(e).__name__}: {e}"
        cluster.stop()
        if saved_breaker is None:
            os.environ.pop("SEAWEEDFS_TPU_BREAKER", None)
        else:
            os.environ["SEAWEEDFS_TPU_BREAKER"] = saved_breaker
        shutil.rmtree(d, ignore_errors=True)
        return out

    out["pids"] = cluster.pids()
    out["distinct_pids"] = len(set(out["pids"].values())) == len(
        out["pids"]
    )

    async def body() -> None:
        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.command.benchmark import fake_payload
        from seaweedfs_tpu.ops.loadgen import (
            LogHistogram,
            ZipfKeys,
            arrival_count,
            run_open_loop,
        )
        from seaweedfs_tpu.s3.auth import sign_request
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        http = FastHTTPClient(pool_per_host=96)
        maddr = cluster.master_address
        s3addr = cluster.address("s3")
        t_leg0 = time.perf_counter()

        def capped() -> bool:
            return time.perf_counter() - t_leg0 > time_cap_s

        def payload(tidx: int, i: int, size: int) -> bytes:
            # tenant-disjoint seed space: any cross-tenant fid/entry
            # mixup is a guaranteed byte mismatch
            return fake_payload((tidx << 56) | i, size)

        def signed_headers(method, url, body_b, n):
            signed = sign_request(
                method, url, {}, body_b, f"AK{n}", f"SK{n}"
            )
            return {
                k: v for k, v in signed.items() if k.lower() != "host"
            }

        try:
            # ---- phase 1: corpus ----
            s3_per_tenant = int(total_keys * s3_fraction / tenants)
            raw_per_tenant = (
                total_keys - s3_per_tenant * tenants
            ) // tenants

            async def fetch_lease(count: int):
                for _ in range(8):
                    try:
                        return await http_assign(http, maddr, count)
                    except RuntimeError as e:
                        if "503" not in str(e):
                            raise
                        await asyncio.sleep(
                            max(0.05, min(
                                http.retry_after_remaining(maddr), 1.0
                            ))
                        )
                return await http_assign(http, maddr, count)

            lease = AssignLease(fetch=fetch_lease, batch=4096)
            fids: list = [[] for _ in range(tenants)]
            errors = [0]
            write_sheds = [0]
            violations = [0]
            isolation_violations = [0]

            async def req_with_retry(method, host, target, **kw):
                # writers HONOR the admission plane: 503 sleeps out the
                # Retry-After floor and retries; sheds are disclosed
                st = resp = None
                for _ in range(8):
                    st, resp = await http.request(
                        method, host, target, **kw
                    )
                    if st != 503:
                        return st, resp
                    write_sheds[0] += 1
                    await asyncio.sleep(
                        max(0.02, min(
                            http.retry_after_remaining(host), 1.0
                        ))
                    )
                return st, resp

            t0 = time.perf_counter()
            work: list = []
            for tidx in range(tenants):
                i = 0
                while i < raw_per_tenant:
                    n = min(batch, raw_per_tenant - i)
                    work.append((tidx, i, n))
                    i += n
            work.reverse()  # pop() drains tenant-interleaved
            stopped = [False]

            async def raw_writer() -> None:
                while work and not stopped[0]:
                    if capped():
                        stopped[0] = True
                        return
                    tidx, start, n = work.pop()
                    items = []
                    for j in range(n):
                        ar = await lease.take()
                        items.append((ar, start + j))
                    parts = [struct.pack("<I", len(items))]
                    for ar, idx in items:
                        fb = ar.fid.encode()
                        body_b = payload(tidx, idx, key_bytes)
                        parts.append(
                            struct.pack("<HI", len(fb), len(body_b))
                        )
                        parts.append(fb)
                        parts.append(body_b)
                    url = items[0][0].url
                    st, resp = await req_with_retry(
                        "POST", url, "/!batch/put",
                        body=b"".join(parts),
                        content_type="application/octet-stream",
                        headers={"X-Seaweed-Tenant": names[tidx]},
                    )
                    if st != 200:
                        errors[0] += n
                        continue
                    results = json.loads(resp)
                    for (ar, idx), r in zip(items, results):
                        if r.get("err"):
                            st2, _ = await req_with_retry(
                                "POST", ar.url, "/" + ar.fid,
                                body=payload(tidx, idx, key_bytes),
                                content_type="application/octet-stream",
                                headers={
                                    "X-Seaweed-Tenant": names[tidx]
                                },
                            )
                            if st2 != 201:
                                errors[0] += 1
                                continue
                        fids[tidx].append((ar.fid, ar.url, idx))

            await asyncio.gather(
                *(raw_writer() for _ in range(write_workers))
            )
            raw_written = sum(len(f) for f in fids)
            out["raw_keys_written"] = raw_written
            out["raw_write_wall_s"] = round(time.perf_counter() - t0, 2)
            out["raw_write_qps"] = round(
                raw_written / max(time.perf_counter() - t0, 1e-9)
            )
            if not raw_written:
                out["error"] = "corpus write produced no fids"
                return

            # S3 objects: per-tenant buckets under bucket-scoped creds
            t0 = time.perf_counter()
            s3_objs: list = [[] for _ in range(tenants)]
            for tidx, n in enumerate(names):
                st, _ = await http.request(
                    "PUT", s3addr, f"/soak-{n}",
                    headers=signed_headers(
                        "PUT", f"http://{s3addr}/soak-{n}", b"",
                        "soakadmin",
                    ),
                )
                if st != 200:
                    out["error"] = f"bucket create for {n}: {st}"
                    return
            s3_work = [
                (tidx, i)
                for i in range(s3_per_tenant)
                for tidx in range(tenants)
            ]
            s3_work.reverse()

            async def s3_writer() -> None:
                while s3_work and not stopped[0]:
                    if capped():
                        stopped[0] = True
                        return
                    tidx, i = s3_work.pop()
                    n = names[tidx]
                    body_b = payload(tidx, (1 << 48) | i, s3_obj_bytes)
                    url = f"http://{s3addr}/soak-{n}/k{i:08d}"
                    st, _ = await req_with_retry(
                        "PUT", s3addr, f"/soak-{n}/k{i:08d}",
                        body=body_b,
                        content_type="application/octet-stream",
                        headers=signed_headers("PUT", url, body_b, n),
                    )
                    if st == 200:
                        s3_objs[tidx].append(i)
                    else:
                        errors[0] += 1

            await asyncio.gather(
                *(s3_writer() for _ in range(write_workers))
            )
            s3_written = sum(len(o) for o in s3_objs)
            out["s3_keys_written"] = s3_written
            out["keys_written"] = raw_written + s3_written
            out["write_errors"] = errors[0]
            out["write_sheds_honored"] = write_sheds[0]
            out["time_capped"] = stopped[0]
            if stopped[0]:
                out["note_cap"] = (
                    f"write phase stopped at time_cap_s={time_cap_s}: "
                    f"{out['keys_written']} of {total_keys} keys — "
                    "acceptance target NOT met this run"
                )

            # vacuum feed: delete a slice so compaction has real work
            deleted = [0]
            for tidx in range(tenants):
                cut = int(len(fids[tidx]) * delete_fraction)
                doomed, fids[tidx] = (
                    fids[tidx][:cut], fids[tidx][cut:]
                )
                for fid, url, _idx in doomed:
                    st, _ = await http.request("DELETE", url, "/" + fid)
                    if st < 300:
                        deleted[0] += 1
            out["keys_deleted"] = deleted[0]

            # ---- tenant-isolation probe: every tenant's creds against
            # its NEIGHBOR's object must be denied ----
            denied = 0
            probes = 0
            for tidx in range(tenants):
                other = (tidx + 1) % tenants
                if not s3_objs[other]:
                    continue
                n_mine, n_other = names[tidx], names[other]
                i = s3_objs[other][0]
                url = f"http://{s3addr}/soak-{n_other}/k{i:08d}"
                st, _ = await http.request(
                    "GET", s3addr, f"/soak-{n_other}/k{i:08d}",
                    headers=signed_headers("GET", url, b"", n_mine),
                )
                probes += 1
                if st == 200:
                    isolation_violations[0] += 1
                else:
                    denied += 1
            out["isolation_probes"] = probes
            out["isolation_denied"] = denied

            # ---- phase 2: chaos soak ----
            # closed-loop calibration: the read ceiling the offered
            # rate anchors against
            all_fids = [
                (tidx, fid, url, idx)
                for tidx in range(tenants)
                for fid, url, idx in fids[tidx]
            ]
            cal_hist = LogHistogram()
            cal_q = list(range(0, len(all_fids), max(
                1, len(all_fids) // 1200
            )))[:1200]
            t0 = time.perf_counter()

            async def cal_worker() -> None:
                while cal_q:
                    k = cal_q.pop()
                    tidx, fid, url, idx = all_fids[k]
                    t1 = time.perf_counter()
                    st, _b = await http.request(
                        "GET", url, "/" + fid, timeout=read_timeout_s
                    )
                    if st == 200:
                        cal_hist.record(time.perf_counter() - t1)

            n_cal = len(cal_q)
            await asyncio.gather(*(cal_worker() for _ in range(16)))
            ceiling = n_cal / max(time.perf_counter() - t0, 1e-9)
            out["closed_loop_ceiling_qps"] = round(ceiling)
            offered = max(50.0, ceiling * offered_fraction)
            out["offered_qps"] = round(offered)

            # seeded process-fault schedule: restart/pause cycles over
            # the volume fleet + one hard filer kill, reproducible from
            # `seed` alone (regenerated + compared below)
            vol_targets = [f"volume-{i}" for i in range(volumes)]

            def build_schedule() -> list:
                sched = process_fault_schedule(
                    seed, vol_targets, soak_window_s * 0.75,
                    count=fault_count, kinds=("restart", "pause"),
                    start_s=soak_window_s * 0.1, pause_s=1.0,
                )
                if filers >= 2:
                    sched += process_fault_schedule(
                        seed + 1, [f"filer-{filers - 1}"],
                        soak_window_s * 0.5, count=1, kinds=("kill",),
                        start_s=soak_window_s * 0.2,
                    )
                return sorted(
                    sched, key=lambda f: (f.at_s, f.target, f.kind)
                )

            schedule = build_schedule()
            out["fault_schedule"] = process_schedule_to_dicts(schedule)
            out["schedule_reproducible"] = (
                process_schedule_to_dicts(build_schedule())
                == out["fault_schedule"]
            )

            zipf = ZipfKeys(
                len(all_fids), s=1.1, seed=seed, cold_fraction=0.05
            )
            n_arr = arrival_count(offered, soak_window_s)
            keys = zipf.draw(n_arr).tolist()
            rng = np.random.default_rng(seed)
            is_write = (rng.random(n_arr) < write_mix).tolist()
            chaos_writes = []  # (tidx, marker_idx, fid, url)
            wctr = [0]
            read_ok = LogHistogram()
            fg_errors = [0]

            async def soak_op(i: int) -> bool:
                if is_write[i]:
                    # foreground write stream: new keys keep arriving
                    # while processes die — landed fids are verified
                    # at quiesce
                    tidx = i % tenants
                    widx = (1 << 52) | wctr[0]
                    wctr[0] += 1
                    try:
                        ar = await lease.take()
                        st, _ = await http.request(
                            "POST", ar.url, "/" + ar.fid,
                            body=payload(tidx, widx, key_bytes),
                            content_type="application/octet-stream",
                            headers={"X-Seaweed-Tenant": names[tidx]},
                            timeout=read_timeout_s,
                        )
                    except Exception:
                        fg_errors[0] += 1
                        return False
                    if st == 201:
                        chaos_writes.append(
                            (tidx, widx, ar.fid, ar.url)
                        )
                        return True
                    fg_errors[0] += 1
                    return False
                tidx, fid, url, idx = all_fids[keys[i]]
                t1 = time.perf_counter()
                try:
                    st, body_b = await http.request(
                        "GET", url, "/" + fid, timeout=read_timeout_s
                    )
                except Exception:
                    fg_errors[0] += 1
                    return False
                if st != 200:
                    fg_errors[0] += 1
                    return False
                if body_b != payload(tidx, idx, key_bytes):
                    violations[0] += 1
                    return False
                read_ok.record(time.perf_counter() - t1)
                return True

            cluster.run_fault_schedule(schedule)
            res = await run_open_loop(
                soak_op, rate=offered, duration=soak_window_s,
                seed=seed, workers=128,
            )
            cluster.join_fault_schedule(timeout=soak_window_s + 60)
            out["soak"] = res.summary()
            out["soak"]["service_rtt"] = read_ok.summary_ms()
            out["soak"]["errors"] = fg_errors[0]
            out["chaos_writes_landed"] = len(chaos_writes)
            goodput = res.completed / max(res.duration, 1e-9)
            out["goodput_qps"] = round(goodput)
            out["goodput_over_offered"] = round(
                goodput / max(offered, 1e-9), 3
            )
            out["fg_p99_ms"] = out["soak"]["p99_ms"]

            # ---- phase 3: quiesce + SLO scoring ----
            # every pause has resumed (driver joined + resume timers
            # are schedule-bounded); give straggling SIGCONTs a beat
            await asyncio.sleep(1.5)
            out["fault_events"] = cluster.fault_events
            fired = [
                e for e in cluster.fault_events if "error" not in e
            ]
            kinds_fired = sorted({e["kind"] for e in fired})
            out["process_faults_fired"] = len(fired)
            out["process_fault_kinds"] = kinds_fired
            restarted = [
                e for e in fired
                if e["kind"] == "restart" and e.get("pid_after")
            ]
            out["sigkill_recovered"] = bool(
                restarted
                and all(
                    e["pid_after"] != e["pid_before"] for e in restarted
                )
            )

            # post-chaos byte identity: a sample per tenant THROUGH the
            # restarted processes, plus every landed chaos write
            post_verified = 0
            for tidx in range(tenants):
                for fid, url, idx in fids[tidx][:24]:
                    st, body_b = await http.request(
                        "GET", url, "/" + fid, timeout=read_timeout_s
                    )
                    if st != 200:
                        fg_errors[0] += 1
                        continue
                    if body_b != payload(tidx, idx, key_bytes):
                        violations[0] += 1
                    post_verified += 1
            for tidx, widx, fid, url in chaos_writes[:256]:
                st, body_b = await http.request(
                    "GET", url, "/" + fid, timeout=read_timeout_s
                )
                if st != 200:
                    fg_errors[0] += 1
                    continue
                if body_b != payload(tidx, widx, key_bytes):
                    violations[0] += 1
                post_verified += 1
            out["post_chaos_reads_verified"] = post_verified

            # S3 read-back (isolation-scoped creds, byte-verified)
            s3_verified = 0
            for tidx in range(tenants):
                n = names[tidx]
                for i in s3_objs[tidx][:50]:
                    url = f"http://{s3addr}/soak-{n}/k{i:08d}"
                    st, body_b = await http.request(
                        "GET", s3addr, f"/soak-{n}/k{i:08d}",
                        headers=signed_headers("GET", url, b"", n),
                    )
                    if st != 200:
                        errors[0] += 1
                        continue
                    if body_b != payload(
                        tidx, (1 << 48) | i, s3_obj_bytes
                    ):
                        violations[0] += 1
                    s3_verified += 1
            out["s3_reads_verified"] = s3_verified
            out["identity_violations"] = violations[0]
            out["isolation_violations"] = isolation_violations[0]

            # maintenance queues drained: poll the master's queue-depth
            # gauges to 0 (scrape = the only window into a subprocess)
            queue_metrics = {
                "repair": "seaweedfs_tpu_repair_queue_depth",
                "vacuum": "seaweedfs_tpu_vacuum_queue_depth",
                "lifecycle": "seaweedfs_tpu_lifecycle_queue_depth",
            }
            deadline = time.monotonic() + quiesce_timeout_s
            depths = {}
            while True:
                m = cluster.scrape_metrics("master")
                depths = {
                    k: sum_metric(m, v)
                    for k, v in queue_metrics.items()
                }
                if all(v == 0 for v in depths.values()):
                    break
                if time.monotonic() > deadline:
                    break
                await asyncio.sleep(0.5)
            out["queue_depths_at_quiesce"] = depths
            out["queues_drained"] = all(
                v == 0 for v in depths.values()
            )

            # plane activity + bloom disclosure from the live children
            mm = cluster.scrape_metrics("master")
            planes = {
                "faults_injected": 0.0,
                "scrub_bytes": 0.0,
                "resyncs": sum_metric(
                    mm, "seaweedfs_tpu_antientropy_resyncs_total"
                ),
            }
            bloom = {
                "runs": 0, "runs_with_filter": 0, "probes": 0,
                "negatives": 0,
            }
            for i in range(volumes):
                name = f"volume-{i}"
                if not cluster.children[name].alive():
                    continue
                vm = cluster.scrape_metrics(name)
                planes["faults_injected"] += sum_metric(
                    vm, "seaweedfs_tpu_faults_injected_total"
                )
                planes["scrub_bytes"] += sum_metric(
                    vm, "seaweedfs_tpu_scrub_bytes_total"
                )
                try:
                    nm = cluster.debug_json(name, "/debug/needle_map")
                    for k in bloom:
                        bloom[k] += nm["aggregate"].get(k, 0)
                except Exception:
                    pass
            bloom["filter_hit_rate"] = (
                round(bloom["negatives"] / bloom["probes"], 4)
                if bloom["probes"] else 0.0
            )
            out["plane_activity"] = planes
            out["bloom"] = bloom

            # ---- SLO scorecard ----
            out["slo"] = {
                "goodput_floor": goodput_floor,
                "goodput_ok": bool(
                    out["goodput_over_offered"] >= goodput_floor
                ),
                "p99_ceiling_ms": p99_ceiling_ms,
                "p99_ok": bool(out["fg_p99_ms"] <= p99_ceiling_ms),
                "identity_violations": violations[0],
                "isolation_violations": isolation_violations[0],
                "queues_drained": out["queues_drained"],
                "faults_fired": len(fired),
                "sigkill_recovered": out["sigkill_recovered"],
            }
            out["slo"]["pass"] = bool(
                out["slo"]["goodput_ok"]
                and out["slo"]["p99_ok"]
                and violations[0] == 0
                and isolation_violations[0] == 0
                and out["queues_drained"]
                and len(fired) >= 2
                and out["sigkill_recovered"]
            )
        finally:
            await http.close()

    try:
        asyncio.run(body())
    except Exception as e:
        out.setdefault("error", f"{type(e).__name__}: {e}")
    finally:
        cluster.stop()
        if saved_breaker is None:
            os.environ.pop("SEAWEEDFS_TPU_BREAKER", None)
        else:
            os.environ["SEAWEEDFS_TPU_BREAKER"] = saved_breaker
        shutil.rmtree(d, ignore_errors=True)
    return out


def measure_geo_soak(
    pre_files: int = 30,
    during_files: int = 30,
    post_files: int = 15,
    payload_bytes: int = 2048,
    partition_start_s: float = 12.0,
    partition_duration_s: float = 8.0,
    lag_bound_s: float = 30.0,
    drain_timeout_s: float = 60.0,
    p99_ceiling_ms: float = 500.0,
    time_cap_s: float = 240.0,
    seed: int = 7,
) -> dict:
    """soak.geo leg (ISSUE 19): two REAL subprocess clusters in two DCs
    with async geo-replication between them, scored through a WAN
    partition.

    Cluster A (dc-a) is the primary; cluster B (dc-b) runs a filer with
    `-geoSource` tailing A's meta-log and shipping chunk bytes. B's filer
    child carries a windowed `wan_partition_plan` naming EVERY listen
    address of A (HTTP + gRPC twins), so `partition_duration_s` seconds
    of hard WAN cut fire inside the subprocess `partition_start_s`
    seconds after it imports — all cross-cluster traffic originates at
    the second site, so cutting its egress IS the WAN link.

    Phases: (1) pre-corpus on A, wait for B to converge (replication
    provably live before the cut); (2) keep writing on A through the
    partition window while sampling A-side read latency and B's
    GeoStatus (connected flag, lag); (3) after heal, write a post batch
    and wait for full drain, then diff the namespaces byte-for-byte.

    SLO terms (vs_baseline = 1 only if ALL hold): primary writes NEVER
    failed during the cut; the cut was actually observed (disconnect or
    lag >= half the window — a leg that never partitioned proves
    nothing); post-heal lag drains under `lag_bound_s`; ZERO lost and
    ZERO duplicated mutations (namespace diff: no missing, no extra, no
    byte mismatch — split-brain shows up as extra/mismatch); primary
    same-DC read p99 under `p99_ceiling_ms` THROUGH the partition."""
    import asyncio
    import hashlib
    import shutil
    import tempfile

    from seaweedfs_tpu.ops.proc_cluster import (
        ProcCluster,
        sum_metric,
        wan_partition_plan,
    )
    from seaweedfs_tpu.pb import grpc_address
    from seaweedfs_tpu.pb.rpc import Stub

    d = tempfile.mkdtemp(
        prefix="bench_geo_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {
        "seed": seed,
        "partition": {
            "start_s": partition_start_s,
            "duration_s": partition_duration_s,
            "scope": "second-site egress (all cross-cluster calls)",
        },
    }
    t_leg0 = time.perf_counter()

    def capped() -> bool:
        return time.perf_counter() - t_leg0 > time_cap_s

    def payload(i: int) -> bytes:
        h = hashlib.sha256(f"{seed}:{i}".encode()).digest()
        return (h * (payload_bytes // len(h) + 1))[:payload_bytes]

    a = ProcCluster(
        os.path.join(d, "A"), volumes=1, filers=1,
        data_center="dc-a", racks=["r0"], durable_filers=True,
    )
    b = None
    try:
        a.start()
        fa = a.address("filer-0")
        a_addrs = [a.master_address, a.address("volume-0"), fa]
        plan = wan_partition_plan(
            a_addrs, start=partition_start_s,
            duration=partition_duration_s, seed=seed,
        )
        b = ProcCluster(
            os.path.join(d, "B"), volumes=1, filers=1,
            data_center="dc-b", racks=["r0"], durable_filers=True,
            geo_source=fa, fault_plans={"filer-0": plan},
        )
        b.start()
        fb = b.address("filer-0")
        out["pids"] = {"A": a.pids(), "B": b.pids()}
    except Exception as e:
        out["error"] = f"cluster start: {type(e).__name__}: {e}"
        if b is not None:
            b.stop()
        a.stop()
        shutil.rmtree(d, ignore_errors=True)
        return out
    t_b_up = time.perf_counter()

    async def body() -> None:
        from seaweedfs_tpu.ops.loadgen import LogHistogram
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        http = FastHTTPClient(pool_per_host=16)
        fa = a.address("filer-0")
        fb = b.address("filer-0")
        geo_stub = Stub(grpc_address(fb), "filer")
        written: list = []
        write_failures = 0
        read_hist = LogHistogram()
        geo_samples: list = []
        max_lag = 0.0
        disconnects = 0

        async def put(i: int) -> None:
            nonlocal write_failures
            st, _ = await http.request(
                "PUT", fa, f"/geo/f{i}.bin", body=payload(i),
                content_type="application/octet-stream", timeout=10.0,
            )
            if st in (200, 201):
                written.append(i)
            else:
                write_failures += 1

        async def sample_geo() -> dict:
            nonlocal max_lag, disconnects
            try:
                g = await geo_stub.call("GeoStatus", {}, timeout=5.0)
            except Exception as e:
                g = {"error": str(e)}
            geo_samples.append(g)
            if g.get("configured"):
                max_lag = max(max_lag, float(g.get("last_lag_seconds", 0)))
                if not g.get("connected"):
                    disconnects += 1
            return g

        async def wait_applied(target: int, timeout: float) -> bool:
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < timeout and not capped():
                g = await sample_geo()
                if int(g.get("applied", 0)) >= target:
                    return True
                await asyncio.sleep(0.3)
            return False

        try:
            # ---- phase 1: pre-corpus, prove replication is live ----
            for i in range(pre_files):
                await put(i)
            out["pre_converged"] = await wait_applied(pre_files, 30.0)

            # ---- phase 2: write THROUGH the partition window ----
            # the window clock starts when the B-filer CHILD imports the
            # faults module (env-delivered plans are install-relative),
            # which is up to a few seconds before b.start() returned —
            # so the window opens somewhere in [t_b_up + start_s -
            # startup_gap, t_b_up + start_s]. Pace the during-writes
            # EVENLY across the whole plausible span so several
            # mutations are guaranteed to land inside the cut, instead
            # of bursting them before it opens.
            t_end = t_b_up + partition_start_s + partition_duration_s + 4.0
            span = max(t_end - time.perf_counter() - 1.0, 1.0)
            pace = span / max(during_files, 1)
            i = pre_files
            next_put = time.perf_counter()
            while time.perf_counter() < t_end and not capped():
                if (
                    i < pre_files + during_files
                    and time.perf_counter() >= next_put
                ):
                    await put(i)
                    i += 1
                    next_put += pace
                # same-DC read against the PRIMARY filer: the partition
                # must not touch it
                j = written[i % len(written)] if written else 0
                t0 = time.perf_counter()
                st, body_b = await http.request(
                    "GET", fa, f"/geo/f{j}.bin", timeout=10.0
                )
                if st == 200:
                    read_hist.record(time.perf_counter() - t0)
                await sample_geo()
                await asyncio.sleep(min(pace, 0.3))
            while i < pre_files + during_files and not capped():
                await put(i)
                i += 1

            # ---- phase 3: heal, post batch, drain, verify ----
            for k in range(during_files + pre_files,
                           pre_files + during_files + post_files):
                await put(k)
            total = len(written)
            out["drained"] = await wait_applied(total, drain_timeout_s)

            missing = extra = mismatch = 0
            for k in written:
                st, got = await http.request(
                    "GET", fb, f"/geo/f{k}.bin", timeout=10.0
                )
                if st != 200:
                    missing += 1
                elif bytes(got) != payload(k):
                    mismatch += 1
            ls = await Stub(grpc_address(fb), "filer").call(
                "ListEntries", {"directory": "/geo", "limit": 4096},
                timeout=10.0,
            )
            peer_names = {
                e["full_path"].rsplit("/", 1)[-1]
                for e in ls.get("entries", [])
                if not e.get("is_directory")
            }
            extra = len(
                peer_names - {f"f{k}.bin" for k in written}
            )
            g = await sample_geo()
            # ground truth from INSIDE the child: the partition seam's
            # own fire counter, scraped off the B-filer /metrics
            try:
                pm = b.scrape_metrics("filer-0")
                faults_fired = int(
                    sum_metric(pm, "seaweedfs_tpu_faults_injected_total")
                )
            except Exception:
                faults_fired = -1

            out.update(
                files_written=total,
                write_failures=write_failures,
                missing_on_peer=missing,
                extra_on_peer=extra,
                byte_mismatches=mismatch,
                applied=int(g.get("applied", 0)),
                skipped=int(g.get("skipped", 0)),
                retried=int(g.get("retried", 0)),
                resync_required=bool(g.get("resync_required")),
                max_lag_s=round(max_lag, 3),
                post_heal_lag_s=float(g.get("last_lag_seconds", 0.0)),
                lag_p99_s=float(g.get("lag_p99_seconds", 0.0)),
                disconnect_samples=disconnects,
                partition_faults_fired=faults_fired,
                # "observed" needs BOTH the seam firing in-child AND a
                # visible degradation signal (stalled apply shows up as
                # lag >= a quarter of the window, a cut stream as a
                # disconnect or retry) — a window that expired during
                # child startup proves nothing and must fail the SLO
                partition_observed=(
                    faults_fired > 0
                    and (
                        disconnects > 0
                        or int(g.get("retried", 0)) > 0
                        or max_lag >= partition_duration_s * 0.25
                    )
                ),
                primary_read_p99_ms=round(
                    read_hist.percentile(99) * 1e3, 2
                )
                if read_hist.count
                else None,
                time_capped=capped(),
            )
            out["slo"] = {
                "writes_survived_partition": write_failures == 0,
                "partition_observed": out["partition_observed"],
                "zero_lost": missing == 0 and mismatch == 0,
                "zero_dup": extra == 0,
                "lag_drained": bool(out["drained"])
                and out["post_heal_lag_s"] <= lag_bound_s,
                "primary_p99_held": (
                    read_hist.count > 0
                    and read_hist.percentile(99) * 1e3 <= p99_ceiling_ms
                ),
                "no_resync_required": not out["resync_required"],
            }
            out["slo"]["pass"] = all(out["slo"].values())
        finally:
            await http.close()

    try:
        asyncio.run(body())
    except Exception as e:
        out.setdefault("error", f"{type(e).__name__}: {e}")
    finally:
        b.stop()
        a.stop()
        shutil.rmtree(d, ignore_errors=True)
    return out


def _dispatch_tracing_overhead_us(sample: float, iters: int = 100000) -> float:
    """Per-request cost of the tracing plane on the serving fast path,
    measured in situ as (enabled block) - (disabled check): a tight loop
    over EXACTLY the work `ServingCore._dispatch` adds per request when
    the recorder is enabled — header probe, inlined sampling coin, two
    clock reads, `note_root` into the live-p99 tracker, the slow-path
    compare, and (for the `sample` fraction that wins the coin) the full
    begin_request/finish span cost. Keep in sync with
    `server/serving_core.py::_dispatch`."""
    import time as _time

    from seaweedfs_tpu.util import trace

    rec = trace.Recorder()
    rec.configure(enabled=True, sample=sample)
    headers = {b"host": b"bench", b"user-agent": b"overhead"}
    _perf = _time.perf_counter
    _coin = trace._rand.random

    def enabled_block() -> None:
        sp = None
        tp = headers.get(b"traceparent")
        pctx = trace.parse_traceparent(tp) if tp is not None else None
        if pctx is not None or (
            rec.sample > 0.0 and _coin() < rec.sample
        ):
            sp = trace.begin_request(
                "volume:GET", pctx,
                server="volume", addr="bench", path="/x",
            )
        t0 = _perf()
        dt = _perf() - t0
        if sp is None:
            rec.note_root(dt)
            if dt > rec.slow_s:
                pass
        else:
            if sp.parent_id == 0:
                rec.note_root(dt)
            sp.finish()

    def disabled_check() -> None:
        if rec.enabled:
            pass

    # begin_request/ActiveSpan.finish go through the module-global
    # RECORDER, so swap a private one in for the measurement and restore
    # after — the real flight recorder's counters/ring stay untouched
    saved = trace.RECORDER
    try:
        trace.RECORDER = rec
        for fn in (enabled_block, disabled_check):  # warm both paths
            for _ in range(2000):
                fn()
        rec.configure(enabled=True, sample=sample)
        t0 = _perf()
        for _ in range(iters):
            enabled_block()
        t_on = _perf() - t0
        rec.enabled = False
        t0 = _perf()
        for _ in range(iters):
            disabled_check()
        t_off = _perf() - t0
    finally:
        trace.RECORDER = saved
    return max((t_on - t_off) / iters * 1e6, 0.0)


def measure_trace_overhead(
    num_files: int = 6000,
    duration: float = 6.0,
    sample: float = 0.01,
    flip_s: float = 0.1,
    rate: Optional[float] = None,
) -> dict:
    """serving.trace_overhead leg (ISSUE 8): the open-loop read leg run
    tracing-OFF vs tracing-ON at `sample` (default 1%) in the SAME credit
    window, disclosing the throughput delta — the price of the always-on
    flight recorder on the volume read hot path.

    Two disclosed measurements:

    - **Macro A/B** (`qps_off` / `qps_on` / `on_over_off_macro`): ONE
      continuous saturated open-loop stream (offered at the inline
      trivial-200 ping rate) with the recorder toggled off<->on every
      `flip_s` (jittered so periodic cluster work can't phase-lock into
      one mode); requests, wall and process-CPU attributed per flip
      window. Honest but noisy: per-window throughput on a shared host
      swings ±15-20% (scheduling bursts, neighbor cache pressure; GC
      ruled out by experiment), so the macro ratio carries a ±3-5%
      standard error — disclosed via `window_qps_stdev_pct`.
    - **The acceptance comparison** (`on_over_off`): the tracing
      plane's per-request cost measured in situ
      (`_dispatch_tracing_overhead_us`: exactly the work the serving
      fast tier adds per request when enabled, sampled spans included)
      divided into the macro stream's measured per-request service
      time — deterministic to ~±0.1µs where the macro A/B's noise floor
      is an order of magnitude above the ~0.5% effect under test.

    The zero-allocation claim is asserted structurally: with the lookup
    gate off, a head-sampled volume read records exactly ONE root span,
    so `ring admissions == sampled roots + tail promotions` — admissions
    scale with the sampled count, never with the request count.
    """
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_trace_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {
        "num_files": num_files,
        "sample": sample,
        "duration_s": duration,
    }
    free_port_pair = _free_port_pair

    async def body() -> None:
        from seaweedfs_tpu.client import MasterClient
        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.client.read_fanout import ReplicaReader
        from seaweedfs_tpu.ops.loadgen import (
            ZipfKeys,
            arrival_count,
            run_open_loop,
        )
        from seaweedfs_tpu.pb.rpc import close_all_channels
        from seaweedfs_tpu.server.master import MasterServer
        from seaweedfs_tpu.server.volume import VolumeServer
        from seaweedfs_tpu.util import trace
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient

        ms = MasterServer(port=free_port_pair(), pulse_seconds=0.2)
        await ms.start()
        vs = VolumeServer(
            master=ms.address,
            directories=[d],
            port=free_port_pair(),
            pulse_seconds=0.2,
            max_volume_counts=[20],
        )
        await vs.start()
        mc = MasterClient("bench-trace-overhead", [ms.address])
        await mc.start()
        http = FastHTTPClient(pool_per_host=160)
        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)
            await mc.wait_connected()

            # --- corpus: 1KB objects via the zero-copy write tier ---
            from seaweedfs_tpu.command.benchmark import fake_payload

            async def fetch_lease(count: int):
                return await http_assign(http, ms.address, count)

            lease = AssignLease(fetch=fetch_lease, batch=128)
            fids: list = []
            widx = [0]

            async def write_worker() -> None:
                while True:
                    i = widx[0]
                    if i >= num_files:
                        return
                    widx[0] = i + 1
                    ar = await lease.take()
                    st, _ = await http.request(
                        "POST", ar.url, "/" + ar.fid,
                        body=fake_payload(i, 1024),
                        content_type="application/octet-stream",
                    )
                    if st == 201:
                        fids.append(ar.fid)

            await asyncio.gather(*(write_worker() for _ in range(16)))
            out["corpus_files"] = len(fids)
            if not fids:
                out["error"] = "corpus write produced no fids"
                return

            zipf = ZipfKeys(len(fids), s=1.1, seed=11, cold_fraction=0.05)
            reader = ReplicaReader(http, mc.vid_map)
            vids = {int(f.split(",")[0]) for f in fids}
            for _ in range(100):
                if all(mc.vid_map.lookup(v) for v in vids):
                    break
                await asyncio.sleep(0.1)
            warm_q = list(range(len(fids)))

            async def warm_worker() -> None:
                while warm_q:
                    k = warm_q.pop()
                    await reader.read_nowait(fids[k])

            await asyncio.gather(*(warm_worker() for _ in range(16)))

            # same-credit-window offered rate (see serving.open_loop)
            out["inline_ping_qps"] = (
                await _trivial_ping_qps(http, 12000, 16)
            )["ping_qps"]
            offered = float(rate or out["inline_ping_qps"])
            out["offered_qps"] = round(offered)

            # one CONTINUOUS open-loop stream with the recorder toggled
            # off<->on every `flip_s`: both modes share every noise
            # regime (container scheduling, neighbor cache pressure,
            # credit throttling drift at >= flip_s timescales), which a
            # slice-paired A/B cannot guarantee — measured slice-pair
            # ratios swung ±3-5% on this host, an order of magnitude
            # above the ~0.75µs/request effect under test. Requests are
            # attributed to the mode active at arrival; wall and
            # process-CPU are attributed per flip window (in-flight
            # requests straddle a boundary for ~req_duration/flip_s of
            # traffic, symmetrically in both directions).
            import gc

            rec = trace.RECORDER
            rec.configure(enabled=False, sample=sample)
            mode_box = ["off"]
            wall_s = {"off": 0.0, "on": 0.0}
            cpu_s = {"off": 0.0, "on": 0.0}
            requests = {"off": 0, "on": 0}
            stop = asyncio.Event()

            import random as _random

            flip_rnd = _random.Random(23)

            window_log: list = []  # (mode, wall_s, requests) per window
            last_req = [0, 0]  # [off, on] request counts at last flip

            async def flipper() -> None:
                last_wall = time.perf_counter()
                last_cpu = time.process_time()
                while not stop.is_set():
                    try:
                        # jittered window length: a fixed flip interval
                        # can phase-lock with periodic cluster work (the
                        # 0.2s heartbeat pulse is exactly 2x a 0.1s
                        # flip), silently billing heartbeats to one mode
                        # for a whole run
                        await asyncio.wait_for(
                            stop.wait(),
                            flip_s * (0.6 + 0.8 * flip_rnd.random()),
                        )
                    except asyncio.TimeoutError:
                        pass
                    now_wall = time.perf_counter()
                    now_cpu = time.process_time()
                    cur = mode_box[0]
                    w = now_wall - last_wall
                    wall_s[cur] += w
                    cpu_s[cur] += now_cpu - last_cpu
                    i = 1 if cur == "on" else 0
                    window_log.append(
                        (cur, round(w, 4), requests[cur] - last_req[i])
                    )
                    last_req[i] = requests[cur]
                    last_wall, last_cpu = now_wall, now_cpu
                    if stop.is_set():
                        return
                    nxt = "on" if cur == "off" else "off"
                    mode_box[0] = nxt
                    rec.enabled = nxt == "on"

            n = arrival_count(offered, duration)
            keys = zipf.draw(n).tolist()

            async def op(i: int) -> bool:
                requests[mode_box[0]] += 1
                st, _body = await reader.read_nowait(fids[keys[i]])
                return st == 200

            gc.collect()
            flip_task = asyncio.ensure_future(flipper())
            try:
                await run_open_loop(
                    op, rate=offered, duration=duration, seed=19,
                    workers=64,
                )
            finally:
                stop.set()
                await flip_task
                rec.enabled = True

            out["flip_s"] = flip_s
            out["qps_off"] = round(
                requests["off"] / max(wall_s["off"], 1e-9)
            )
            out["qps_on"] = round(
                requests["on"] / max(wall_s["on"], 1e-9)
            )
            # macro A/B ratio over the interleaved windows — DISCLOSED
            # WITH ITS NOISE: per-window throughput on this class of
            # shared host swings ±15-20% (loop scheduling bursts,
            # neighbor cache pressure; GC ruled out by a gc.disable
            # experiment), so over a seconds-scale stream this ratio
            # carries a ±3-5% standard error, an order of magnitude
            # above the ~0.5% effect under test. It is reported for
            # honesty, not used as the acceptance comparison.
            out["on_over_off_macro"] = round(
                out["qps_on"] / max(out["qps_off"], 1), 4
            )
            wq = [r / w for _m, w, r in window_log if w >= 0.03]
            out["window_count"] = len(wq)
            if len(wq) >= 2:
                import statistics as _stats

                out["window_qps_stdev_pct"] = round(
                    _stats.pstdev(wq) / max(_stats.mean(wq), 1e-9) * 100,
                    1,
                )
            # supporting detail: process-CPU per request per mode
            out["cpu_us_per_request_off"] = round(
                cpu_s["off"] / max(requests["off"], 1) * 1e6, 2
            )
            out["cpu_us_per_request_on"] = round(
                cpu_s["on"] / max(requests["on"], 1) * 1e6, 2
            )

            # the DISCLOSED comparison: the per-request cost of the
            # tracing plane measured in situ (a tight loop over exactly
            # the work ServingCore._dispatch adds when tracing is
            # enabled, coin + clocks + note_root + the amortized sampled
            # span at this `sample`), divided into the macro stream's
            # measured per-request service time. Deterministic to
            # ~±0.1µs where the macro A/B is ±3-5% — the construction is
            # disclosed in the note and docs/observability.md.
            overhead_us = _dispatch_tracing_overhead_us(sample)
            service_us = 1e6 / max(out["qps_off"], out["qps_on"], 1)
            out["overhead_us_per_request"] = round(overhead_us, 3)
            out["service_us_per_request"] = round(service_us, 1)
            out["on_over_off"] = round(
                service_us / (service_us + max(overhead_us, 0.0)), 4
            )

            # --- zero-alloc fast path: admissions == sampled count ---
            st = rec.status()
            admitted = st["admitted"]
            sampled = st["sampled_roots"]
            promoted = (
                st["promoted_slow"] + st["promoted_flagged"]
                + st["promoted_fault"]
            )
            out["trace_requests"] = requests["on"]
            out["ring_admissions"] = admitted
            out["sampled_roots"] = sampled
            out["tail_promotions"] = promoted
            out["admissions_equal_sampled"] = (
                admitted == sampled + promoted
            )
            out["sampled_fraction"] = round(
                sampled / max(requests["on"], 1), 4
            )
        finally:
            trace.RECORDER.configure(enabled=True, sample=0.01)
            await http.close()
            await mc.stop()
            await vs.stop()
            await ms.stop()
            await close_all_channels()

    try:
        asyncio.run(body())
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out


def measure_s3_gateway(
    num_objects: int = 3000,
    obj_bytes: int = 1024,
    list_keys: int = 10000,
    max_keys: int = 100,
    get_duration: float = 4.0,
    concurrency: int = 16,
    zipf_s: float = 1.1,
) -> dict:
    """Object-gateway legs (ISSUE 7 tentpole): s3.put_qps / s3.get_qps /
    s3.list_qps through the full master + volume + filer + S3 stack,
    next to the RAW volume-tier legs measured in the SAME credit window
    (the acceptance ratio: gateway >= 0.5x raw on each verb).

    - raw legs: closed-loop c=16 leased direct-to-volume PUTs, then
      closed-loop random GETs of the same fids — the volume tier's own
      numbers for this host and moment;
    - s3.put: closed-loop c=16 PutObject through the gateway fast tier;
      the handler's s3_stage_seconds partition (auth/meta/lease/upload/
      render) is differenced across the leg and published as an
      itemized per-request budget with coverage_of_p50 (the
      serving_write_budget methodology applied to the gateway);
    - s3.get: the open-loop harness (ops/loadgen.py) at the
      same-credit-window inline trivial-200 ping rate, zipf-popular
      keys, CO-corrected p50/p99/p999; plus an in-leg byte-identity
      check of gateway GETs against direct volume reads of the same
      chunks;
    - s3.list: ListObjectsV2 pages (max-keys) over a bucket >= 100x the
      page size, walked via continuation tokens; per-request
      scanned-entries from the range-scan counter disclose that LIST
      work is O(max-keys), not O(bucket), and one full walk is checked
      against the expected sorted key set.
    """
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_s3_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {
        "num_objects": num_objects,
        "obj_bytes": obj_bytes,
        "list_keys": list_keys,
        "max_keys": max_keys,
        "concurrency": concurrency,
    }
    free_port_pair = _free_port_pair

    async def body() -> None:
        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.command.benchmark import fake_payload
        from seaweedfs_tpu.ops.loadgen import (
            LogHistogram,
            ZipfKeys,
            arrival_count,
            run_open_loop,
        )
        from seaweedfs_tpu.pb.rpc import close_all_channels
        from seaweedfs_tpu.s3.server import S3Server
        from seaweedfs_tpu.server.filer import FilerServer
        from seaweedfs_tpu.server.master import MasterServer
        from seaweedfs_tpu.server.volume import VolumeServer
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient
        from seaweedfs_tpu.util.metrics import (
            S3_LIST_SCANNED,
            S3_STAGE_SECONDS,
        )

        ms = MasterServer(port=free_port_pair(), pulse_seconds=0.2)
        await ms.start()
        vs = VolumeServer(
            master=ms.address,
            directories=[d],
            port=free_port_pair(),
            pulse_seconds=0.2,
            max_volume_counts=[20],
        )
        await vs.start()
        fs = FilerServer(
            master=ms.address,
            port=free_port_pair(),
            store_path=os.path.join(d, "meta.lsm"),
        )
        http = FastHTTPClient(pool_per_host=160)
        s3 = None
        s3_started = fs_started = False
        try:
            for _ in range(100):
                if ms.topo.data_nodes():
                    break
                await asyncio.sleep(0.1)
            # start the filer BEFORE scanning for the S3 port: the scan
            # only sees ports that are actually bound
            await fs.start()
            fs_started = True
            await fs.master_client.wait_connected()
            s3 = S3Server(fs, port=free_port_pair())
            await s3.start()
            s3_started = True
            st, _ = await http.request("PUT", s3.address, "/bench")
            if st != 200:
                out["error"] = f"create bucket: {st}"
                return

            # same-credit-window trivial-200 floor (shared helper)
            out["inline_ping_qps"] = (
                await _trivial_ping_qps(http, 12000, concurrency)
            )["ping_qps"]

            # --- raw volume-tier reference legs (same window) ---
            async def fetch_lease(count: int):
                return await http_assign(http, ms.address, count)

            lease = AssignLease(fetch=fetch_lease, batch=128)
            fids: list = []
            idx = [0]
            payload = fake_payload(11, obj_bytes)

            async def raw_writer() -> None:
                while True:
                    i = idx[0]
                    if i >= num_objects:
                        return
                    idx[0] = i + 1
                    ar = await lease.take()
                    st, _ = await http.request(
                        "POST", ar.url, "/" + ar.fid, body=payload,
                        content_type="application/octet-stream",
                    )
                    if st == 201:
                        fids.append(ar.fid)

            t0 = time.perf_counter()
            await asyncio.gather(*(raw_writer() for _ in range(concurrency)))
            out["raw_put_qps"] = round(
                len(fids) / max(time.perf_counter() - t0, 1e-9)
            )
            if not fids:
                out["error"] = "raw write leg produced no fids"
                return

            n_reads = min(3 * num_objects, 12000)
            ridx = [0]
            rng = np.random.default_rng(5)
            read_order = rng.integers(0, len(fids), size=n_reads).tolist()

            async def raw_reader() -> None:
                while True:
                    i = ridx[0]
                    if i >= n_reads:
                        return
                    ridx[0] = i + 1
                    await http.request(
                        "GET", vs.address, "/" + fids[read_order[i]]
                    )

            t0 = time.perf_counter()
            await asyncio.gather(*(raw_reader() for _ in range(concurrency)))
            out["raw_get_qps"] = round(
                n_reads / max(time.perf_counter() - t0, 1e-9)
            )

            # --- s3.put: closed-loop PutObject through the fast tier ---
            stages = ("auth", "meta", "lease", "upload", "render")
            before = {
                s: S3_STAGE_SECONDS.sum_count(verb="PUT", stage=s)
                for s in stages
            }
            keys = [f"o/{i:07d}" for i in range(num_objects)]
            widx = [0]
            put_hist = LogHistogram()
            put_fail = [0]

            async def s3_writer() -> None:
                while True:
                    i = widx[0]
                    if i >= num_objects:
                        return
                    widx[0] = i + 1
                    t1 = time.perf_counter()
                    # same constant payload as the raw leg: the client-side
                    # payload synthesis must not asymmetrically tax the
                    # gateway leg's closed loop
                    st, _ = await http.request(
                        "PUT", s3.address, "/bench/" + keys[i],
                        body=payload,
                        content_type="application/octet-stream",
                    )
                    put_hist.record(time.perf_counter() - t1)
                    if st != 200:
                        put_fail[0] += 1

            t0 = time.perf_counter()
            await asyncio.gather(*(s3_writer() for _ in range(concurrency)))
            put_wall = max(time.perf_counter() - t0, 1e-9)
            out["put_qps"] = round((num_objects - put_fail[0]) / put_wall)
            out["put_failed"] = put_fail[0]
            out["put_latency_ms"] = put_hist.summary_ms()
            # itemized per-request stage budget (server-side partition of
            # the handler wall, differenced across the leg)
            budget: dict = {}
            for s in stages:
                s1, c1 = S3_STAGE_SECONDS.sum_count(verb="PUT", stage=s)
                s0, c0 = before[s]
                n = max(c1 - c0, 1)
                budget[f"{s}_us"] = round((s1 - s0) / n * 1e6, 1)
            budget["component_sum_us"] = round(
                sum(v for v in budget.values()), 1
            )
            p50_us = put_hist.percentile(50) * 1e6
            budget["put_p50_us"] = round(p50_us, 1)
            budget["coverage_of_p50"] = round(
                budget["component_sum_us"] / max(p50_us, 1e-9), 3
            )
            out["s3_stage_budget"] = budget
            out["put_vs_raw"] = round(
                out["put_qps"] / max(out["raw_put_qps"], 1), 3
            )

            # --- s3.get: open-loop zipfian GETs at the inline ping rate ---
            zipf = ZipfKeys(len(keys), s=zipf_s, seed=13)
            offered = float(out["inline_ping_qps"])
            sched = zipf.draw(arrival_count(offered, get_duration)).tolist()

            async def get_op(i: int) -> bool:
                st, _ = await http.request(
                    "GET", s3.address, "/bench/" + keys[sched[i]]
                )
                return st == 200

            oc = s3.object_cache
            hits0 = oc.hits if oc else 0
            miss0 = oc.misses if oc else 0
            res = await run_open_loop(
                get_op, rate=offered, duration=get_duration, seed=3,
                workers=64,
            )
            if oc is not None:
                hits, misses = oc.hits - hits0, oc.misses - miss0
                out["object_cache"] = {
                    **oc.stats(),
                    "leg_hits": hits,
                    "leg_misses": misses,
                    "hit_rate": round(hits / max(hits + misses, 1), 4),
                }
            else:
                out["object_cache"] = {"disabled": True, "hit_rate": 0.0}
            out["get_open_loop"] = res.summary()
            out["get_qps"] = out["get_open_loop"]["achieved_qps"]
            out["get_vs_raw"] = round(
                out["get_qps"] / max(out["raw_get_qps"], 1), 3
            )
            out["get_over_ping"] = round(
                out["get_qps"] / max(out["inline_ping_qps"], 1), 3
            )

            # --- byte identity: gateway GET == direct volume read ---
            ident = True
            for i in range(0, num_objects, max(1, num_objects // 16))[:16]:
                entry = fs.filer.find_entry(f"/buckets/bench/{keys[i]}")
                if entry is None:
                    continue  # that PUT failed (counted in put_failed)
                st_a, a = await http.request(
                    "GET", s3.address, "/bench/" + keys[i]
                )
                direct = bytearray()
                for c in sorted(entry.chunks, key=lambda c: c.offset):
                    st_b, blob = await http.request(
                        "GET", vs.address, "/" + c.fid
                    )
                    if st_b != 200:
                        ident = False
                    direct += blob
                if not (st_a == 200 and bytes(direct) == a):
                    ident = False
            out["gateway_direct_identical"] = ident

            # --- s3.list: range-scan ListObjectsV2 over a big bucket ---
            st, _ = await http.request("PUT", s3.address, "/listbench")
            n_dirs = 50
            for i in range(list_keys):
                fs.filer.touch(
                    f"/buckets/listbench/d{i % n_dirs:02d}/k{i:07d}", "", []
                )
            scanned0 = sum(S3_LIST_SCANNED._values.values())
            list_hist = LogHistogram()
            walked: list = []
            requests = [0]
            token = [""]
            t0 = time.perf_counter()
            # full pagination walks until the time budget is spent; each
            # request is one max-keys page
            list_budget_s = min(3.0, get_duration)
            full_walks = [0]
            while time.perf_counter() - t0 < list_budget_s:
                target = f"/listbench?list-type=2&max-keys={max_keys}"
                if token[0]:
                    target += f"&continuation-token={token[0]}"
                t1 = time.perf_counter()
                st, body_ = await http.request("GET", s3.address, target)
                list_hist.record(time.perf_counter() - t1)
                requests[0] += 1
                if st != 200:
                    out["list_error"] = f"status {st}"
                    break
                import xml.etree.ElementTree as ET

                tree = ET.fromstring(body_)
                page_keys = [
                    c.findtext("Key") for c in tree.findall("Contents")
                ]
                if full_walks[0] == 0:
                    walked.extend(page_keys)
                if tree.findtext("IsTruncated") == "true":
                    token[0] = tree.findtext("NextContinuationToken")
                else:
                    token[0] = ""
                    full_walks[0] += 1
            wall = max(time.perf_counter() - t0, 1e-9)
            scanned1 = sum(S3_LIST_SCANNED._values.values())
            out["list_qps"] = round(requests[0] / wall)
            out["list_requests"] = requests[0]
            out["list_latency_ms"] = list_hist.summary_ms()
            out["list_scanned_per_request"] = round(
                (scanned1 - scanned0) / max(requests[0], 1), 1
            )
            out["list_scan_bounded"] = (
                out["list_scanned_per_request"] <= 4 * (max_keys + n_dirs)
            )
            expect = sorted(
                f"d{i % n_dirs:02d}/k{i:07d}" for i in range(list_keys)
            )
            if full_walks[0] >= 1:
                out["list_walk_complete"] = walked == expect
            out["list_full_walks"] = full_walks[0]
        finally:
            await http.close()
            try:
                if s3_started:
                    await s3.stop()
            except Exception:
                pass
            try:
                if fs_started:
                    await fs.stop()
            except Exception:
                pass
            await vs.stop()
            await ms.stop()
            await close_all_channels()

    try:
        asyncio.run(body())
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out


class _Skip(Exception):
    """Secondary metric skipped: bench budget spent."""


_E2E_NOTE = (
    "streamed depth-N pipeline (ring-staged chunks, kernel dispatch "
    "overlaps next read + previous shard writes); on the CPU stand-in the "
    "kernel stage dispatches the native host codec (kernel_dispatch="
    "host_standin) instead of round-tripping jax-on-CPU — on a real TPU "
    "the same ring uploads to the device (kernel_dispatch=device); see "
    "measure_encode_e2e"
)


def _clean_stale_e2e_dirs() -> None:
    """A SIGKILLed child skips its finally-cleanup; reclaim its tmpfs files
    so later runs aren't demoted off /dev/shm by the free-space check."""
    import glob
    import shutil
    import tempfile

    for base in ("/dev/shm", tempfile.gettempdir()):
        for d in glob.glob(os.path.join(base, "bench_ec_e2e_*")):
            shutil.rmtree(d, ignore_errors=True)


def _e2e_results(r: dict) -> list:
    """Bench `extra` entries from a (possibly partial) measure_encode_e2e
    result dict. vs_baseline is each pipeline over the reference-style leg
    (single-thread 256KB loop, SIMD codec — the ec_encoder.go:120-136
    stand-in measured on the same host and files)."""
    out = []
    ref = r.get("ref_gbps")
    ref_info = {"baseline_gbps": round(ref, 3)} if ref else {}
    if "tpu_gbps" in r:
        entry = {
            "metric": "ec.encode.e2e",
            "value": round(r["tpu_gbps"], 3),
            "unit": "GB/s",
            "vs_baseline": round(r["tpu_gbps"] / ref, 2) if ref else None,
            "shards_byte_identical": r.get("tpu_parity"),
            "note": _E2E_NOTE,
        }
        stages = r.get("tpu_stages")
        if stages:
            # the streamed pipeline's per-stage walls (ISSUE 17): read/
            # stage/sync (+splice/calibrate) are the main-thread stages
            # and PARTITION the wall — their sum over total_s is
            # coverage_of_wall; kernel_s and write_s run on the pool and
            # writer threads and are the OVERLAPPED walls (deliberately
            # not summed: overlap is the point)
            entry["stage_breakdown"] = stages
            if "coverage_of_wall" in stages:
                entry["coverage_of_wall"] = stages["coverage_of_wall"]
            if "pipeline_depth" in stages:
                entry["pipeline_depth"] = stages["pipeline_depth"]
        route = r.get("tpu_route")
        if route:
            entry["route"] = route
            if "kernel" in route:
                entry["kernel_dispatch"] = route["kernel"]
        if "device_status" in r:
            entry["device_status"] = r["device_status"]
        if "tpu_size_bytes" in r:
            entry["size_bytes"] = r["tpu_size_bytes"]
        out.append(entry)
    elif "error" in r:
        # the leg that died is the first one whose result is absent — keep
        # the measured baseline so a partial run still records evidence
        if not ref:
            died = "baseline"
        elif "best_gbps" not in r:
            died = "best"
        else:
            died = "device"
        out.append(
            {
                "metric": "ec.encode.e2e",
                "error": f"{died} leg failed: {r['error']}",
                **ref_info,
            }
        )
    if "best_gbps" in r:
        entry = {
            "metric": "ec.encode.e2e.best",
            "value": round(r["best_gbps"], 3),
            "unit": "GB/s",
            "vs_baseline": round(r["best_gbps"] / ref, 2) if ref else None,
            "shards_byte_identical": r.get("best_parity"),
            "backend": r.get("best_backend"),
            "baseline_gbps": round(ref, 3) if ref else None,
            "size_bytes": r.get("size_bytes"),
            "tmpfs": r.get("tmpfs"),
            "note": "shipping adaptive route (tpu/coder.adaptive_codec) "
            "vs the reference-structure single-thread 256KB pipeline",
        }
        # bandwidth context: memcpy/best = how many memcpy-equivalents of
        # work the route spends per source byte (a memcpy itself moves
        # each byte over the bus twice, so the floor for a pipeline that
        # reads the source once and materializes 1.4 bytes of shards is
        # ~1.2 memcpy-equivalents). Values near the floor mean the route
        # is memory-bandwidth-bound on this host, not compute- or
        # structure-bound. Measured inside measure_encode_e2e's timebox.
        mem = r.get("host_memcpy_gbps")
        if mem:
            entry["host_memcpy_gbps"] = mem
            entry["memcpy_equiv_per_byte"] = round(
                mem / max(r["best_gbps"], 1e-9), 2
            )
        stages = r.get("best_stages")
        if stages:
            # stage breakdown of the winning run (VERDICT §5): does the
            # GF kernel bound the shipped e2e number, or the file legs?
            total = stages.get("total_s") or sum(
                v for k, v in stages.items() if k.endswith("_s")
            )
            kern = stages.get("kernel_s", stages.get("fused_s", 0.0))
            entry["stage_breakdown"] = {
                **stages,
                "kernel_share": round(kern / max(total, 1e-9), 3),
                "note": (
                    "fused_s = single-sweep native route (read/encode/"
                    "write interleaved, not separable); on the mmap route "
                    ".dat page-fault reads land inside kernel_s/"
                    "shard_write_s, so kernel_share is an UPPER bound on "
                    "the kernel's true share; ecx_s=0 because "
                    "write_ec_files never writes .ecx (that belongs to "
                    "volume->EC conversion). kernel_share < ~0.5 means "
                    "further host-kernel work cannot move this number "
                    "much — the file legs bound it"
                ),
            }
        legs = r.get("io_legs")
        if legs:
            # the e2e roofline (VERDICT r4 item 8): ceilings built from
            # measured FILE-leg unit costs in the same throttle window —
            # memcpy overstates this host's file IO by 2-4x (fresh tmpfs
            # writes fault+zero pages, reads allocate), which is why
            # memcpy_equiv_per_byte ~5 looked like headroom that file IO
            # physics doesn't actually offer. Two bounds, route-aware:
            # every route reads the source once and fresh-writes parity;
            # a route that fresh-writes data shards too (onepass/inline)
            # pays 1.4/W, one that splices them kernel-side pays ~1.0/W
            # of kernel copy + 1.4/memcpy of encode passes instead.
            R, W = legs["read_gbps"], legs["fresh_write_gbps"]
            mem_bw = r.get("host_memcpy_gbps") or 8.0
            c_fresh = 1.0 / (1.0 / R + 1.4 / W)
            c_splice = 1.0 / (
                1.0 / R + 1.0 / W + 0.4 / W + 1.4 / mem_bw
            )
            route = r.get("best_route", {})
            applicable = c_splice if route.get("spliced") else c_fresh
            entry["e2e_roofline"] = {
                **legs,
                "route": route,
                "ceiling_fresh_gbps": round(c_fresh, 3),
                "ceiling_spliced_gbps": round(c_splice, 3),
                "fraction_of_ceiling": round(
                    r["best_gbps"] / max(applicable, 1e-9), 2
                ),
                "model": "fresh: 1/(1/R + 1.4/W); spliced: 1/(1/R + "
                "1.4/W + 1.4/memcpy) with data shards kernel-copied at "
                "~W; fraction is vs the executed route's bound",
            }
        out.append(entry)
    return out


def _run_e2e_timeboxed(time_left: float = 600.0) -> list:
    """Run measure_encode_e2e in a subprocess with a hard wall-clock box:
    the tunnel's transfer rate swings 10x between runs, and a slow run must
    cost this one metric, not the whole benchmark. The child prints the
    partial result dict after every leg, so a timeout keeps the completed
    legs. On single-client TPU backends (directly attached, device already
    held by this process) the child cannot open the device, so we fall back
    to running inline (untimeboxed)."""
    import subprocess
    import sys

    t_enter = time.perf_counter()

    def left_now() -> float:
        return time_left - (time.perf_counter() - t_enter)

    def parse_last(text: str):
        for line in reversed((text or "").strip().splitlines()):
            try:
                d = json.loads(line)
                if isinstance(d, dict) and "ref_gbps" in d:
                    return d
            except (json.JSONDecodeError, ValueError):
                continue
        return None

    try:
        e2e_bytes = int(os.environ.get("BENCH_EC_E2E_BYTES", 4 << 30))
        # stay INSIDE the caller's remaining budget (margin for the final
        # print); an env override still wins for manual runs
        timeout = float(
            os.environ.get("BENCH_EC_E2E_TIMEOUT", max(40.0, time_left - 15))
        )
        _clean_stale_e2e_dirs()
        script = (
            "import json, sys, bench\n"
            "def emit(r):\n"
            "    print(json.dumps(r)); sys.stdout.flush()\n"
            f"bench.measure_encode_e2e({e2e_bytes}, emit=emit)\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        r = parse_last(out.stdout)
        if out.returncode != 0:
            err = (out.stderr or out.stdout)[-400:]
            if r is None:
                if "in use" in err or "already" in err.lower():
                    # device is single-client: run inline instead — but only
                    # with real budget left NOW (the subprocess may have
                    # burned most of it), since inline has no timebox
                    if left_now() > 180:
                        return _e2e_results(measure_encode_e2e(e2e_bytes))
                    return [
                        {
                            "metric": "ec.encode.e2e",
                            "error": "single-client device and bench budget "
                            "too low for an untimeboxed inline run",
                        }
                    ]
                return [{"metric": "ec.encode.e2e", "error": err[-200:]}]
            # partial result + crash (e.g. device leg died): keep the
            # completed legs but surface the failure on the device metric
            r.setdefault("error", err[-200:])
        return _e2e_results(r or {"error": "no output"})
    except subprocess.TimeoutExpired as te:
        _clean_stale_e2e_dirs()
        stdout = te.stdout
        if isinstance(stdout, bytes):
            stdout = stdout.decode(errors="replace")
        r = parse_last(stdout)
        if r is not None:
            r.setdefault("error", "timed out (tunnel-bound); partial result")
            return _e2e_results(r)
        return [
            {
                "metric": "ec.encode.e2e",
                "error": "timed out (tunnel-bound; rerun with "
                "BENCH_EC_E2E_TIMEOUT/BENCH_EC_E2E_BYTES)",
            }
        ]
    except Exception as e:
        return [{"metric": "ec.encode.e2e", "error": str(e)[:200]}]


_SHARDED_EC_NOTE = (
    "parallel/sharded_ec shard_map over the (vol, blk) device mesh; "
    "vs_baseline = mesh over the SAME formulation pinned to 1 device. On "
    "the CPU stand-in the mesh is virtual host devices "
    "(--xla_force_host_platform_device_count) — correctness + dispatch "
    "overhead proof, not real scale-out; device_status says which"
)


def _run_sharded_timeboxed(time_left: float = 120.0) -> list:
    """ec.encode.sharded + ec.rebuild.sharded entries from a subprocess
    run of measure_sharded_ec. A subprocess because the virtual multi-chip
    stand-in needs --xla_force_host_platform_device_count in XLA_FLAGS
    BEFORE jax initializes, which this process has long since done; on a
    real TPU the flag is omitted and the mesh uses the real chips."""
    import subprocess
    import sys

    status = _device_status()
    env = dict(os.environ)
    if status != "tpu":
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        ).strip()
        env.setdefault("JAX_PLATFORMS", "cpu")
    script = (
        "import json, sys, bench\n"
        "print(json.dumps(bench.measure_sharded_ec()))\n"
        "sys.stdout.flush()\n"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True,
            text=True,
            timeout=max(40.0, time_left),
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        sh = None
        for line in reversed((out.stdout or "").strip().splitlines()):
            try:
                d = json.loads(line)
                if isinstance(d, dict) and "encode_gbps_mesh" in d:
                    sh = d
                    break
            except (json.JSONDecodeError, ValueError):
                continue
        if sh is None:
            err = (out.stderr or out.stdout or "no output")[-200:]
            return [
                {"metric": "ec.encode.sharded", "error": err},
                {"metric": "ec.rebuild.sharded", "error": err},
            ]
        return [
            {
                "metric": "ec.encode.sharded",
                "value": sh["encode_gbps_mesh"],
                "unit": "GB/s",
                "vs_baseline": sh.get("encode_scaling"),
                "shards_byte_identical": sh.get("encode_identical"),
                "device_status": status,
                "detail": sh,
                "note": _SHARDED_EC_NOTE,
            },
            {
                "metric": "ec.rebuild.sharded",
                "value": sh["rebuild_gbps_mesh"],
                "unit": "GB/s",
                "vs_baseline": sh.get("rebuild_scaling"),
                "shards_byte_identical": sh.get("rebuild_identical"),
                "device_status": status,
                "detail": sh,
                "note": _SHARDED_EC_NOTE,
            },
        ]
    except subprocess.TimeoutExpired:
        return [
            {"metric": "ec.encode.sharded", "error": "timed out"},
            {"metric": "ec.rebuild.sharded", "error": "timed out"},
        ]
    except Exception as e:
        msg = str(e)[:200]
        return [
            {"metric": "ec.encode.sharded", "error": msg},
            {"metric": "ec.rebuild.sharded", "error": msg},
        ]


def measure_lifecycle_convergence(
    n_cold_volumes: int = 4,
    cold_files_per_volume: int = 8,
    cold_file_bytes: int = 256 * 1024,
    fg_files: int = 1500,
    fg_bytes: int = 1024,
    window_s: float = 3.0,
    maint_mbps: float = 40.0,
    fg_rate_fraction: float = 0.4,
) -> dict:
    """lifecycle.convergence leg (ISSUE 10): auto-EC conversions run to
    completion UNDER an open-loop foreground read stream, and the
    foreground p99 with conversions in flight is disclosed against a
    no-conversion window of the same shape — the arxiv 1709.05365
    contention check (encode/reconstruct I/O vs foreground serving),
    bounded by the shared MaintenanceBudget + overload-pressure yielding
    (acceptance: ratio <= 1.5x).

    Construction: one master + 3 volume servers on shm; a COLD corpus
    (collection "cold", several volumes of ~MB payloads) written first so
    its write heat decays across the baseline window (short heat
    half-life), and a HOT foreground corpus whose zipfian open-loop read
    stream runs in BOTH windows at the same offered rate (a fraction of
    the same-credit-window inline trivial-200 ping). The conversion
    window drives `run_lifecycle_once` until every cold volume is
    erasure-coded, with all conversion I/O tagged plane="lifecycle" on
    the shared budget. Byte identity: every cold object is read back
    through the EC path and compared to the bytes written."""
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_lc_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {
        "n_cold_volumes": n_cold_volumes,
        "cold_files_per_volume": cold_files_per_volume,
        "cold_file_bytes": cold_file_bytes,
        "fg_files": fg_files,
        "window_s": window_s,
        "maint_mbps": maint_mbps,
    }
    free_port_pair = _free_port_pair
    prev_halflife = os.environ.get("SEAWEEDFS_TPU_HEAT_HALFLIFE")
    os.environ["SEAWEEDFS_TPU_HEAT_HALFLIFE"] = "1.0"

    async def body() -> None:
        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.command.benchmark import fake_payload
        from seaweedfs_tpu.ops.loadgen import ZipfKeys, run_open_loop
        from seaweedfs_tpu.server.master import MasterServer
        from seaweedfs_tpu.server.volume import VolumeServer
        from seaweedfs_tpu.storage.maintenance import (
            MaintenanceBudget,
            configure_shared,
        )
        from seaweedfs_tpu.topology.lifecycle import LifecycleConfig
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient
        from seaweedfs_tpu.util.metrics import LIFECYCLE_CONVERSIONS

        def conversions(direction: str, result: str) -> float:
            key = tuple(
                sorted({"direction": direction, "result": result}.items())
            )
            return LIFECYCLE_CONVERSIONS._values.get(key, 0.0)

        budget = MaintenanceBudget(maint_mbps)
        configure_shared(budget)
        ms = MasterServer(
            port=free_port_pair(),
            pulse_seconds=0.2,
            lifecycle_config=LifecycleConfig(
                cold_read_heat=2.0,
                cold_write_heat=2.0,
                hot_read_heat=10_000.0,  # this leg never re-inflates
                full_fraction=0.0,       # small bench volumes count full
                collections="cold",      # the fg corpus must not convert
            ),
            lifecycle_ec_shards="4.2",
            lifecycle_concurrency=1,  # stretch the contention window
        )
        await ms.start()
        servers = []
        for i in range(3):
            vd = os.path.join(d, f"v{i}")
            os.makedirs(vd, exist_ok=True)
            vs = VolumeServer(
                master=ms.address,
                directories=[vd],
                port=free_port_pair(),
                pulse_seconds=0.2,
                max_volume_counts=[30],
            )
            await vs.start()
            servers.append(vs)
        http = FastHTTPClient(pool_per_host=96)
        try:
            for _ in range(100):
                if len(ms.topo.data_nodes()) == 3:
                    break
                await asyncio.sleep(0.1)

            # --- cold corpus first (its write heat decays from here) ---
            cold_payloads: dict[str, bytes] = {}
            for i in range(n_cold_volumes * cold_files_per_volume):
                st, resp = await http.request(
                    "GET", ms.address,
                    "/dir/assign?collection=cold",
                )
                ar = json.loads(resp)
                if "error" in ar:
                    raise RuntimeError(f"cold assign: {ar['error']}")
                body_b = fake_payload(i, cold_file_bytes)
                st, _ = await http.request(
                    "POST", ar["url"], "/" + ar["fid"], body=body_b,
                    content_type="application/octet-stream",
                )
                if st == 201:
                    cold_payloads[ar["fid"]] = bytes(body_b)
            cold_vids = sorted(
                {int(f.split(",")[0]) for f in cold_payloads}
            )
            out["cold_objects"] = len(cold_payloads)
            out["cold_vids"] = cold_vids
            out["cold_bytes"] = len(cold_payloads) * cold_file_bytes

            # --- foreground corpus (stays hot through both windows) ---
            lease = AssignLease(
                fetch=lambda count: http_assign(http, ms.address, count),
                batch=128,
            )
            fg: list = []
            for i in range(fg_files):
                ar = await lease.take()
                st, _ = await http.request(
                    "POST", ar.url, "/" + ar.fid,
                    body=fake_payload(10_000 + i, fg_bytes),
                    content_type="application/octet-stream",
                )
                if st == 201:
                    fg.append((ar.url, "/" + ar.fid))
            if not fg:
                out["error"] = "foreground corpus write produced no fids"
                return

            out["inline_ping_qps"] = (
                await _trivial_ping_qps(http, 8000, 16)
            )["ping_qps"]
            offered = max(out["inline_ping_qps"] * fg_rate_fraction, 500.0)
            out["offered_qps"] = round(offered)
            zipf = ZipfKeys(len(fg), s=1.1, seed=5)
            keys = zipf.draw(int(offered * window_s * 2.2) + 16).tolist()

            async def fg_op(i: int) -> bool:
                url, path = fg[keys[i % len(keys)]]
                st, _ = await http.request("GET", url, path)
                return st == 200

            # --- baseline window: no conversions in flight ---
            base = await run_open_loop(
                fg_op, rate=offered, duration=window_s, seed=3, workers=48
            )
            out["baseline"] = base.summary()

            # --- conversion window: same stream, lifecycle running ---
            ok0 = conversions("ec", "ok")
            err0 = conversions("ec", "error")

            def all_converted() -> bool:
                return all(
                    ms.topo.lookup("cold", v) is None
                    and ms.topo.lookup_ec_shards(v) is not None
                    for v in cold_vids
                )

            conv_done_at = [None]

            async def drive_conversions() -> None:
                t0 = time.perf_counter()
                for _ in range(400):
                    if all_converted():
                        break
                    r = await ms.run_lifecycle_once()
                    if r.get("error"):
                        break
                    await asyncio.sleep(0.05)
                if all_converted():
                    conv_done_at[0] = time.perf_counter() - t0

            loop_res, _ = await asyncio.gather(
                run_open_loop(
                    fg_op, rate=offered, duration=window_s, seed=4,
                    workers=48,
                ),
                drive_conversions(),
            )
            out["with_conversions"] = loop_res.summary()
            out["converted_all"] = all_converted()
            out["conversion_wall_s"] = (
                round(conv_done_at[0], 3) if conv_done_at[0] else None
            )
            # how much of the conversion wall the measured window saw —
            # a ratio measured over a sliver of the conversions would
            # overstate how benign they are
            if conv_done_at[0]:
                out["window_overlap_of_conversions"] = round(
                    min(window_s, conv_done_at[0]) / conv_done_at[0], 3
                )
            out["conversions_ec_ok"] = conversions("ec", "ok") - ok0
            out["conversions_ec_error"] = conversions("ec", "error") - err0
            out["lifecycle_queue_depth_end"] = ms.lifecycle_queue.depth()
            out["maintenance"] = budget.snapshot()
            p99_base = max(out["baseline"]["p99_ms"], 1e-6)
            out["fg_p99_ratio"] = round(
                out["with_conversions"]["p99_ms"] / p99_base, 3
            )

            # --- byte identity through the EC read path ---
            identical = out["converted_all"]
            for fid, want in cold_payloads.items():
                vid = fid.split(",")[0]
                locs = ms._do_lookup(vid).get("locations") or []
                got = None
                for loc in locs:
                    st, body_r = await http.request(
                        "GET", loc["url"], "/" + fid
                    )
                    if st == 200:
                        got = body_r
                        break
                if got != want:
                    identical = False
                    break
            out["byte_identical"] = identical
        finally:
            await http.close()
            for vs in servers:
                await vs.stop()
            await ms.stop()
            configure_shared(None)
            from seaweedfs_tpu.pb.rpc import close_all_channels

            await close_all_channels()

    try:
        asyncio.run(body())
    finally:
        if prev_halflife is None:
            os.environ.pop("SEAWEEDFS_TPU_HEAT_HALFLIFE", None)
        else:
            os.environ["SEAWEEDFS_TPU_HEAT_HALFLIFE"] = prev_halflife
        shutil.rmtree(d, ignore_errors=True)
    return out


def measure_cold_tier(
    n_cold_volumes: int = 2,
    cold_files_per_volume: int = 6,
    cold_file_bytes: int = 128 * 1024,
    fg_files: int = 800,
    fg_bytes: int = 1024,
    window_s: float = 3.0,
    maint_mbps: float = 12.0,
    fg_rate_fraction: float = 0.3,
) -> dict:
    """lifecycle.cold_tier leg (ISSUE 14): the full offload → remote-read
    → recall arc runs to completion UNDER an open-loop zipf(1.1)
    foreground read stream, against the in-tree HTTP blob server (served
    through ServingCore, so the remote tier pays admission/fault/trace
    costs like any cluster server). Disclosed: recall p99 (per-holder
    walls — the latency a reheating volume pays before it serves at
    local-disk prices), read-through cache hit rate, foreground p99
    with/without ratio (the arxiv 1709.05365 contention check, bounded
    by plane=lifecycle MaintenanceBudget spend + pressure yielding;
    acceptance <= 1.5x), and byte identity at every stage (EC'd /
    offloaded / offloaded-again(cache) / recalled)."""
    import asyncio
    import shutil
    import tempfile

    d = tempfile.mkdtemp(
        prefix="bench_ct_",
        dir="/dev/shm" if os.path.isdir("/dev/shm") else None,
    )
    out: dict = {
        "n_cold_volumes": n_cold_volumes,
        "cold_files_per_volume": cold_files_per_volume,
        "cold_file_bytes": cold_file_bytes,
        "fg_files": fg_files,
        "window_s": window_s,
        "maint_mbps": maint_mbps,
    }
    free_port_pair = _free_port_pair
    prev_halflife = os.environ.get("SEAWEEDFS_TPU_HEAT_HALFLIFE")
    os.environ["SEAWEEDFS_TPU_HEAT_HALFLIFE"] = "0.5"

    async def body() -> None:
        from seaweedfs_tpu.client.operation import AssignLease, http_assign
        from seaweedfs_tpu.command.benchmark import fake_payload
        from seaweedfs_tpu.ops.loadgen import ZipfKeys, run_open_loop
        from seaweedfs_tpu.server.blob import BlobServer
        from seaweedfs_tpu.server.master import MasterServer
        from seaweedfs_tpu.server.volume import VolumeServer
        from seaweedfs_tpu.storage.maintenance import (
            MaintenanceBudget,
            configure_shared,
        )
        from seaweedfs_tpu.storage.tier_backend import (
            BACKEND_STORAGES,
            S3Backend,
            register_backend,
        )
        from seaweedfs_tpu.topology.lifecycle import LifecycleConfig
        from seaweedfs_tpu.util.fasthttp import FastHTTPClient
        from seaweedfs_tpu.util.metrics import (
            TIER_REMOTE_CACHE_HITS,
            TIER_REMOTE_CACHE_MISSES,
        )

        def cache_counts() -> tuple:
            return (
                TIER_REMOTE_CACHE_HITS._values.get((), 0.0),
                TIER_REMOTE_CACHE_MISSES._values.get((), 0.0),
            )

        budget = MaintenanceBudget(maint_mbps)
        configure_shared(budget)
        saved_backends = dict(BACKEND_STORAGES)
        blob = BlobServer(os.path.join(d, "blobs"), port=free_port_pair())
        await blob.start()
        register_backend(S3Backend("cold", f"http://{blob.address}", "tier"))
        ms = MasterServer(
            port=free_port_pair(),
            pulse_seconds=0.2,
            lifecycle_config=LifecycleConfig(
                cold_read_heat=2.0,
                cold_write_heat=2.0,
                hot_read_heat=1e9,  # this leg never re-inflates
                full_fraction=0.0,
                offload_read_heat=0.6,
                recall_read_heat=6.0,
                cold_backend="s3.cold",
                # scope the plane to the cold corpus: once the measured
                # foreground window ends, the (0.5s-half-life) fg corpus
                # cools too, and an unscoped planner would convert +
                # offload all of IT — tens of MB of churn that has
                # nothing to do with the arc under measurement
                collections="cold",
            ),
            lifecycle_ec_shards="4.2",
            lifecycle_concurrency=2,
        )
        await ms.start()
        servers = []
        for i in range(3):
            vd = os.path.join(d, f"v{i}")
            os.makedirs(vd, exist_ok=True)
            vs = VolumeServer(
                master=ms.address,
                directories=[vd],
                port=free_port_pair(),
                pulse_seconds=0.2,
                max_volume_counts=[30],
            )
            await vs.start()
            servers.append(vs)
        http = FastHTTPClient(pool_per_host=96)
        try:
            for _ in range(100):
                if len(ms.topo.data_nodes()) == 3:
                    break
                await asyncio.sleep(0.1)

            # --- cold corpus (heat decays from here) ---
            cold_payloads: dict[str, bytes] = {}
            for i in range(n_cold_volumes * cold_files_per_volume):
                st, resp = await http.request(
                    "GET", ms.address, "/dir/assign?collection=cold"
                )
                ar = json.loads(resp)
                if "error" in ar:
                    raise RuntimeError(f"cold assign: {ar['error']}")
                body_b = fake_payload(i, cold_file_bytes)
                st, _ = await http.request(
                    "POST", ar["url"], "/" + ar["fid"], body=body_b,
                    content_type="application/octet-stream",
                )
                if st == 201:
                    cold_payloads[ar["fid"]] = bytes(body_b)
            cold_vids = sorted({int(f.split(",")[0]) for f in cold_payloads})
            out["cold_objects"] = len(cold_payloads)
            out["cold_vids"] = cold_vids
            out["cold_bytes"] = len(cold_payloads) * cold_file_bytes

            # --- foreground corpus (hot through both windows) ---
            lease = AssignLease(
                fetch=lambda count: http_assign(http, ms.address, count),
                batch=128,
            )
            fg: list = []
            for i in range(fg_files):
                ar = await lease.take()
                st, _ = await http.request(
                    "POST", ar.url, "/" + ar.fid,
                    body=fake_payload(50_000 + i, fg_bytes),
                    content_type="application/octet-stream",
                )
                if st == 201:
                    fg.append((ar.url, "/" + ar.fid))
            if not fg:
                out["error"] = "foreground corpus write produced no fids"
                return

            out["inline_ping_qps"] = (
                await _trivial_ping_qps(http, 8000, 16)
            )["ping_qps"]
            offered = max(out["inline_ping_qps"] * fg_rate_fraction, 500.0)
            out["offered_qps"] = round(offered)
            zipf = ZipfKeys(len(fg), s=1.1, seed=9)
            keys = zipf.draw(int(offered * window_s * 2.2) + 16).tolist()

            async def fg_op(i: int) -> bool:
                url, path = fg[keys[i % len(keys)]]
                st, _ = await http.request("GET", url, path)
                return st == 200

            async def read_cold_all(tag: str) -> bool:
                ok = True
                for fid, want in cold_payloads.items():
                    vid = fid.split(",")[0]
                    locs = ms._do_lookup(vid).get("locations") or []
                    got = None
                    for loc in locs:
                        st, body_r = await http.request(
                            "GET", loc["url"], "/" + fid
                        )
                        if st == 200:
                            got = body_r
                            break
                    if got != want:
                        ok = False
                        break
                return ok

            # cool the cold corpus below BOTH thresholds
            await asyncio.sleep(3.0)

            identity: dict = {}
            recall_walls: list[float] = []
            activity_wall = [None]

            def all_ec() -> bool:
                return all(
                    ms.topo.lookup("cold", v) is None
                    and ms.topo.lookup_ec_shards(v) is not None
                    for v in cold_vids
                )

            def offloaded_everywhere() -> bool:
                for vs in servers:
                    for v in cold_vids:
                        ev = vs.store.find_ec_volume(v)
                        if ev is not None and ev.shards:
                            return False
                return all(
                    any(
                        vs.store.find_ec_volume(v) is not None
                        for vs in servers
                    )
                    for v in cold_vids
                )

            def recalled_everywhere() -> bool:
                held = {v: False for v in cold_vids}
                for vs in servers:
                    for v in cold_vids:
                        ev = vs.store.find_ec_volume(v)
                        if ev is None:
                            continue
                        if ev.remote_shards:
                            return False
                        if ev.shards:
                            held[v] = True
                return all(held.values())

            async def rounds(pred, limit: int, pump=None) -> bool:
                for _ in range(limit):
                    if pred():
                        return True
                    if pump is not None:
                        await pump()
                    r = await ms.run_lifecycle_once()
                    if r.get("error"):
                        return False
                    for ent in r.get("dispatched", []):
                        walls = ent.get("recall_s")
                        if isinstance(walls, dict):
                            recall_walls.extend(walls.values())
                    await asyncio.sleep(0.05)
                return pred()

            # --- setup: EC conversion happens BEFORE any measured
            # window — the arc under measurement is offload → remote
            # read → recall (ISSUE 14); conversion contention is the
            # convergence leg's subject, already measured there ---
            t_ec0 = time.perf_counter()
            ok_ec = await rounds(all_ec, 300)
            identity["ec"] = ok_ec and await read_cold_all("ec")
            out["ec_setup_wall_s"] = round(time.perf_counter() - t_ec0, 3)
            # the identity reads above warmed the corpus: let it cool
            # back below the offload threshold before measuring
            await asyncio.sleep(2.5)

            # --- baseline window: no cold-tier activity ---
            base = await run_open_loop(
                fg_op, rate=offered, duration=window_s, seed=3, workers=48
            )
            out["baseline"] = base.summary()

            async def drive_activity() -> None:
                t0 = time.perf_counter()
                ok_off = await rounds(offloaded_everywhere, 300)
                h0, m0 = cache_counts()
                identity["offloaded"] = (
                    ok_off and await read_cold_all("offloaded")
                )
                identity["offloaded_cached"] = await read_cold_all(
                    "offloaded-again"
                )
                h1, m1 = cache_counts()
                out["cache_hits"] = h1 - h0
                out["cache_misses"] = m1 - m0
                out["cache_hit_rate"] = round(
                    (h1 - h0) / max(h1 - h0 + m1 - m0, 1.0), 4
                )

                async def pump() -> None:
                    # remote reads themselves pump heat past recall
                    await read_cold_all("pump")

                ok_rec = await rounds(recalled_everywhere, 300, pump=pump)
                identity["recalled"] = (
                    ok_rec and await read_cold_all("recalled")
                )
                activity_wall[0] = time.perf_counter() - t0
                # settle: the heartbeat tier-bit refresh lags a tick, so
                # a just-satisfied recall task can sit queued until the
                # next scan's prune sees fresh bits — drain it
                for _ in range(30):
                    r = await ms.run_lifecycle_once()
                    if (
                        not r.get("error")
                        and r.get("queue_depth") == 0
                        and not r.get("dispatched")
                    ):
                        break
                    await asyncio.sleep(0.3)

            loop_res, _ = await asyncio.gather(
                run_open_loop(
                    fg_op, rate=offered, duration=window_s, seed=4,
                    workers=48,
                ),
                drive_activity(),
            )
            out["with_cold_tier"] = loop_res.summary()
            out["identity"] = identity
            out["byte_identical"] = all(identity.values())
            out["activity_wall_s"] = (
                round(activity_wall[0], 3) if activity_wall[0] else None
            )
            if activity_wall[0]:
                out["window_overlap_of_activity"] = round(
                    min(window_s, activity_wall[0]) / activity_wall[0], 3
                )
            out["recall_walls_s"] = [round(w, 4) for w in recall_walls]
            if recall_walls:
                walls = sorted(recall_walls)
                out["recall_p99_ms"] = round(
                    walls[min(len(walls) - 1, int(len(walls) * 0.99))]
                    * 1000.0,
                    3,
                )
                out["recall_max_ms"] = round(walls[-1] * 1000.0, 3)
            out["lifecycle_queue_depth_end"] = ms.lifecycle_queue.depth()
            out["maintenance"] = budget.snapshot()
            p99_base = max(out["baseline"]["p99_ms"], 1e-6)
            out["fg_p99_ratio"] = round(
                out["with_cold_tier"]["p99_ms"] / p99_base, 3
            )
        finally:
            await http.close()
            for vs in servers:
                await vs.stop()
            await ms.stop()
            await blob.stop()
            BACKEND_STORAGES.clear()
            BACKEND_STORAGES.update(saved_backends)
            configure_shared(None)
            from seaweedfs_tpu.pb.rpc import close_all_channels

            await close_all_channels()

    try:
        asyncio.run(body())
    finally:
        if prev_halflife is None:
            os.environ.pop("SEAWEEDFS_TPU_HEAT_HALFLIFE", None)
        else:
            os.environ["SEAWEEDFS_TPU_HEAT_HALFLIFE"] = prev_halflife
        shutil.rmtree(d, ignore_errors=True)
    return out


def _synth_idx(
    path: str,
    n_keys: int,
    overwrite_fraction: float = 0.10,
    delete_fraction: float = 0.05,
    seed: int = 11,
):
    """Synthesize a production-shaped .idx log, fully vectorized: n_keys
    puts, then a shuffled mix of overwrites and deletes, with offsets
    laid out exactly as sequential appends of the claimed sizes would
    land (so the map-layer mount comparison replays a REAL log shape).
    Returns (live_key_count, total_entries, oracle columns)."""
    from seaweedfs_tpu.storage.idx import entries_to_bytes
    from seaweedfs_tpu.storage.needle_map.lsm_map import fold_live_columns
    from seaweedfs_tpu.types import (
        NEEDLE_CHECKSUM_SIZE,
        NEEDLE_HEADER_SIZE,
        NEEDLE_PADDING_SIZE,
        TIMESTAMP_SIZE,
        TOMBSTONE_FILE_SIZE,
    )

    rng = np.random.default_rng(seed)
    n_over = int(n_keys * overwrite_fraction)
    n_del = int(n_keys * delete_fraction)
    keys = np.concatenate(
        [
            np.arange(1, n_keys + 1, dtype=np.uint64),
            rng.integers(1, n_keys + 1, n_over, dtype=np.uint64),
            rng.integers(1, n_keys + 1, n_del, dtype=np.uint64),
        ]
    )
    sizes = rng.integers(128, 4096, len(keys), dtype=np.uint32)
    sizes[n_keys + n_over :] = TOMBSTONE_FILE_SIZE
    # shuffle the tail (overwrites/deletes interleave in real logs)
    tail = rng.permutation(len(keys) - n_keys) + n_keys
    keys[n_keys:] = keys[tail]
    sizes[n_keys:] = sizes[tail]
    # offsets: each record lands where sequential appends put it
    body = np.where(
        sizes == np.uint32(TOMBSTONE_FILE_SIZE), 0, sizes
    ).astype(np.int64)
    base = body + NEEDLE_HEADER_SIZE + NEEDLE_CHECKSUM_SIZE + TIMESTAMP_SIZE
    rec = base + (8 - base % 8)
    starts = 40 + np.concatenate([[0], np.cumsum(rec)[:-1]])
    offsets = (starts // NEEDLE_PADDING_SIZE).astype(np.uint64)
    with open(path, "wb") as f:
        f.write(entries_to_bytes(keys, offsets, sizes))
    live = fold_live_columns(keys, offsets, sizes)
    return len(live[0]), len(keys), live


def measure_needle_map_mount(
    n_keys: int = 2_000_000,
    tail_entries: int = 2_000,
    sample: int = 2_000,
) -> dict:
    """Billion-needle mount path (ISSUE 13 tentpole proof): the same
    multi-million-entry .idx log mounted through

    - `dict` — the memory kind's per-entry replay
      (needle_map.load_needle_map, the pre-ISSUE mount path), and
    - `lsm` — snapshot + tail: mmap the persisted sorted runs and
      replay only the `tail_entries` entries appended past the fold
      frontier (needle_map.load_lsm_needle_map).

    Wall is measured WITHOUT instrumentation; resident bytes come from
    a separate tracemalloc'd load of each (Python-allocator bytes — the
    honest basis: the LSM runs are mmap'd page cache ON PURPOSE and a
    process-RSS delta would re-count them non-deterministically). The
    lsm cold (no-snapshot) rebuild wall is disclosed too: that is the
    one-time cost a volume pays to ENTER the O(tail) regime. Probe
    equivalence over `sample` random keys guards byte-identity."""
    import shutil
    import tempfile
    import tracemalloc

    from seaweedfs_tpu.storage.needle_map import (
        load_lsm_needle_map,
        load_needle_map,
    )
    from seaweedfs_tpu.storage.needle_map.lsm_map import invalidate_snapshot

    use_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    d = tempfile.mkdtemp(prefix="bench_nm_mount_", dir=use_dir)
    out: dict = {"n_keys": n_keys, "tail_entries": tail_entries,
                 "tmpfs": use_dir is not None}
    try:
        idx = os.path.join(d, "1.idx")
        live_n, total, _live = _synth_idx(idx, n_keys)
        out["total_entries"] = total
        out["live_keys"] = live_n

        # --- lsm cold: no snapshot -> vectorized full rebuild ---
        invalidate_snapshot(idx[: -len(".idx")])
        t0 = time.perf_counter()
        nm_cold = load_lsm_needle_map(idx)
        out["mount_lsm_cold_s"] = round(time.perf_counter() - t0, 4)
        assert not nm_cold.loaded_from_snapshot
        nm_cold.close()  # persists the snapshot for the warm leg

        # append a tail past the fold frontier (the restart-after-
        # writes shape the snapshot mount must absorb); both mounts
        # below replay the SAME full log, so answers must agree
        if tail_entries:
            _synth_idx(
                os.path.join(d, "tail.idx"), tail_entries, 0.0, 0.0,
                seed=99,
            )
            with open(os.path.join(d, "tail.idx"), "rb") as f:
                tail_blob = f.read()
            with open(idx, "ab") as f:
                f.write(tail_blob)

        # --- dict replay (the memory kind's mount) ---
        t0 = time.perf_counter()
        nm_dict = load_needle_map(idx)
        out["mount_dict_s"] = round(time.perf_counter() - t0, 4)

        # --- lsm warm: snapshot + tail replay (the shipping mount) ---
        t0 = time.perf_counter()
        nm_lsm = load_lsm_needle_map(idx)
        out["mount_lsm_s"] = round(time.perf_counter() - t0, 4)
        out["loaded_from_snapshot"] = nm_lsm.loaded_from_snapshot
        out["tail_replayed"] = nm_lsm.tail_entries_replayed
        out["snapshot_age_s"] = round(nm_lsm.snapshot_age_s, 3)
        out["mount_speedup"] = round(
            out["mount_dict_s"] / max(out["mount_lsm_s"], 1e-9), 2
        )

        # --- probe equivalence (byte-identical index answers) ---
        rng = np.random.default_rng(3)
        probes = rng.integers(1, n_keys + 1, sample, dtype=np.uint64)
        mismatches = 0
        for k in probes.tolist():
            a, b = nm_dict.get(k), nm_lsm.get(k)
            at = (
                None
                if a is None or a.size == 0xFFFFFFFF
                else (a.offset_units, a.size)
            )
            bt = (
                None
                if b is None or b.size == 0xFFFFFFFF
                else (b.offset_units, b.size)
            )
            if at != bt:
                mismatches += 1
        out["probe_sample"] = sample
        out["probe_mismatches"] = mismatches
        out["identical"] = mismatches == 0
        out["file_counts_equal"] = nm_dict.file_count == nm_lsm.file_count
        nm_dict.close()
        nm_lsm.close()

        # --- resident bytes: separate tracemalloc'd loads ---
        tracemalloc.start()
        before = tracemalloc.get_traced_memory()[0]
        nm = load_needle_map(idx)
        out["resident_dict_bytes"] = (
            tracemalloc.get_traced_memory()[0] - before
        )
        nm.close()
        tracemalloc.stop()
        del nm
        tracemalloc.start()
        before = tracemalloc.get_traced_memory()[0]
        nm = load_lsm_needle_map(idx)
        out["resident_lsm_bytes"] = (
            tracemalloc.get_traced_memory()[0] - before
        )
        assert nm.loaded_from_snapshot
        nm.close()
        tracemalloc.stop()
        out["resident_ratio"] = round(
            out["resident_dict_bytes"]
            / max(out["resident_lsm_bytes"], 1),
            1,
        )
        out["resident_bounded_below_dict"] = (
            out["resident_lsm_bytes"] < out["resident_dict_bytes"]
        )
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_needle_map_lookup(
    n_keys: int = 500_000,
    probes: int = 120_000,
    rate: float = 40_000.0,
    zipf_s: float = 1.1,
) -> dict:
    """Read-hot-path flatness proof for the LSM map: the SAME zipfian
    open-loop probe stream (Poisson arrivals at a fixed offered rate,
    single-threaded) driven against the dict map and the sealed LSM
    map, byte-identical answers asserted entry-wise. Two latency blocks
    per map: per-op SERVICE time (the scored one — for a data-structure
    comparison, a shared host's ~20ms CPU-steal stall must not taint
    ~800 probes' worth of percentile mass) and the coordinated-
    omission-corrected ARRIVAL latency (disclosed alongside: the
    serving-methodology number). The headline is the service p99 ratio
    lsm/dict: the LSM map pays a numpy searchsorted per probe instead
    of a dict hit, and the disclosed factor is the whole cost — at
    serving rates it sits under a ~35µs request wall, so 'flat' here
    means single-digit µs p99, not parity with a dict load."""
    import shutil
    import tempfile

    from seaweedfs_tpu.ops.loadgen import LogHistogram, ZipfKeys
    from seaweedfs_tpu.storage.needle_map import (
        load_lsm_needle_map,
        load_needle_map,
    )

    use_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    d = tempfile.mkdtemp(prefix="bench_nm_lookup_", dir=use_dir)
    out: dict = {
        "n_keys": n_keys, "probes": probes, "offered_rate": rate,
        "zipf_s": zipf_s,
    }
    try:
        idx = os.path.join(d, "1.idx")
        live_n, _total, live = _synth_idx(idx, n_keys)
        live_keys = live[0]
        out["live_keys"] = live_n

        zipf = ZipfKeys(n=live_n, s=zipf_s, seed=5, cold_fraction=0.05)
        out["hot_share_top1pct"] = round(zipf.hot_share(0.01), 4)
        probe_keys = live_keys[zipf.draw(probes)].tolist()
        rng = np.random.default_rng(9)
        gaps = rng.exponential(1.0 / rate, probes)
        sched = np.cumsum(gaps)

        nm_dict = load_needle_map(idx)
        nm_lsm = load_lsm_needle_map(idx)
        nm_lsm.save_snapshot()

        # entry-wise identity first (also warms both maps' pages)
        mismatches = 0
        for k in probe_keys[: min(probes, 20000)]:
            a, b = nm_dict.get(k), nm_lsm.get(k)
            if (a.offset_units, a.size) != (b.offset_units, b.size):
                mismatches += 1
        out["identical"] = mismatches == 0
        out["probe_mismatches"] = mismatches

        def open_loop(nm) -> dict:
            get = nm.get
            svc = LogHistogram()  # per-op service time (probe wall)
            arr = LogHistogram()  # CO-corrected latency from SCHEDULED
            now = time.perf_counter
            t_start = now()
            for i in range(probes):
                t_arr = t_start + sched[i]
                while True:
                    t = now()
                    if t >= t_arr:
                        break
                get(probe_keys[i])
                done = now()
                svc.record(done - t)
                arr.record(done - t_arr)
            wall = now() - t_start
            s, a = svc.summary_ms(), arr.summary_ms()
            return {
                # the scored block: the probe's own wall. On this
                # burst-throttled shared host a single ~20ms CPU-steal
                # stall taints ~800 CO-corrected arrival latencies at
                # the offered rate — a lottery for a DATA-STRUCTURE
                # comparison; the arrival block is still disclosed
                # below because it is the serving-methodology number
                "p50_us": round(s["p50_ms"] * 1e3, 2),
                "p99_us": round(s["p99_ms"] * 1e3, 2),
                "p999_us": round(s["p999_ms"] * 1e3, 2),
                "mean_us": round(s["mean_ms"] * 1e3, 2),
                "arrival_p50_us": round(a["p50_ms"] * 1e3, 2),
                "arrival_p99_us": round(a["p99_ms"] * 1e3, 2),
                "arrival_p999_us": round(a["p999_ms"] * 1e3, 2),
                "achieved_qps": round(probes / wall),
                "achieved_over_offered": round(probes / wall / rate, 3),
            }

        # interleave (shared-host noise): keep each map's best run
        runs = {"dict": None, "lsm": None}
        for rep in range(3):
            order = (
                [("dict", nm_dict), ("lsm", nm_lsm)]
                if rep % 2 == 0
                else [("lsm", nm_lsm), ("dict", nm_dict)]
            )
            for name, nm in order:
                r = open_loop(nm)
                if runs[name] is None or r["p99_us"] < runs[name]["p99_us"]:
                    runs[name] = r
        out["dict"] = runs["dict"]
        out["lsm"] = runs["lsm"]
        out["p99_ratio_lsm_over_dict"] = round(
            runs["lsm"]["p99_us"] / max(runs["dict"]["p99_us"], 1e-6), 2
        )
        out["arrival_p99_ratio"] = round(
            runs["lsm"]["arrival_p99_us"]
            / max(runs["dict"]["arrival_p99_us"], 1e-6),
            2,
        )
        out["lsm_runs"] = len(nm_lsm._runs)
        out["bloom"] = _measure_bloom_detail(d, live_keys)
        nm_dict.close()
        nm_lsm.close()
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def _measure_bloom_detail(
    d: str, live_keys: np.ndarray, absent_probes: int = 30_000
) -> dict:
    """needle_map.lookup detail (ISSUE 15 satellite): a MULTI-run LSM
    map built from the same live set, probed with absent keys — the
    shape the per-run bloom filters exist for (without them every
    absent probe pays one binary search PER run). Disclosed: filter
    hit rate and the absent-key service p99 with filters on vs off
    (same runs, reloaded without sidecars consulted)."""
    from seaweedfs_tpu.ops.loadgen import LogHistogram
    from seaweedfs_tpu.storage.needle_map import lsm_map as _lsm

    idx2 = os.path.join(d, "2.idx")
    nm = _lsm.new_lsm_needle_map(idx2)
    nm.memtable_limit = max(1024, len(live_keys) // 5)
    for i in range(0, len(live_keys), 4096):
        nm.put_batch(
            (int(k), int(k) + 1, 100)
            for k in live_keys[i : i + 4096]
        )
    nm.save_snapshot()

    top = int(live_keys.max())
    absent = (top + 1 + np.arange(absent_probes, dtype=np.uint64)).tolist()
    out: dict = {"runs": len(nm._runs)}

    was = _lsm.BLOOM_ENABLED
    _lsm.BLOOM_ENABLED = False
    try:
        nm_off = _lsm.LsmNeedleMap(idx2)
    finally:
        _lsm.BLOOM_ENABLED = was

    def probe(m) -> dict:
        h = LogHistogram()
        get = m.get
        now = time.perf_counter
        for k in absent:
            t = now()
            get(k)
            h.record(now() - t)
        s = h.summary_ms()
        return {
            "mean_us": round(s["mean_ms"] * 1e3, 2),
            "p99_us": round(s["p99_ms"] * 1e3, 2),
        }

    # interleaved best-of (the leg's shared-host discipline): at µs
    # scales one CPU-steal stall would decide the comparison otherwise
    best = {"bloom": None, "nobloom": None}
    for rep in range(3):
        order = [("bloom", nm), ("nobloom", nm_off)]
        if rep % 2:
            order.reverse()
        for name, m in order:
            r = probe(m)
            if best[name] is None or r["mean_us"] < best[name]["mean_us"]:
                best[name] = r
    out["absent_bloom"] = best["bloom"]
    out["absent_nobloom"] = best["nobloom"]
    st = nm.bloom_stats()
    out["runs_with_filter"] = st["runs_with_filter"]
    out["filter_hit_rate"] = st["filter_hit_rate"]
    # consultation threshold + per-run consult/hit tail (ISSUE 17
    # satellite): which runs actually short-circuit absent probes, so
    # threshold tuning (SEAWEEDFS_TPU_BLOOM_MIN_RUNS) has evidence
    out["min_runs"] = st.get("min_runs")
    out["per_run"] = st.get("per_run")
    out["absent_mean_speedup"] = round(
        best["nobloom"]["mean_us"] / max(best["bloom"]["mean_us"], 1e-6), 2
    )
    nm.close()
    nm_off.close()
    return out


def measure_meta_lookup_qps(
    n_dirs: int = 96,
    files_per_dir: int = 64,
    probes: int = 48_000,
    batch: int = 64,
    n_shards: int = 4,
    zipf_s: float = 1.1,
    reps: int = 3,
) -> dict:
    """meta.lookup_qps leg (ISSUE 15): the SAME zipfian path-probe
    stream against (a) one sqlite filer store probed per-request — the
    single-store metadata plane every request used to funnel through —
    and (b) the prefix-sharded store probed through gate-sized
    `find_many` batches (what `MetaLookupGate` feeds it per event-loop
    wakeup), with the per-shard sub-batches running in parallel worker
    threads. A third leg (single store, batched) is disclosed so the
    batching and sharding contributions separate. Answers are asserted
    entry-identical on a sample; per-op service p99 and scanned work
    (store calls per probe) are disclosed. All legs run interleaved in
    the same credit window; best-of-reps per leg."""
    import shutil
    import tempfile

    from seaweedfs_tpu.filer.entry import Attr, Entry, new_directory_entry
    from seaweedfs_tpu.filer.filer_store import SqliteFilerStore
    from seaweedfs_tpu.filer.sharded_store import ShardedFilerStore
    from seaweedfs_tpu.ops.loadgen import LogHistogram, ZipfKeys

    use_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    d = tempfile.mkdtemp(prefix="bench_meta_lookup_", dir=use_dir)
    out: dict = {
        "n_dirs": n_dirs, "files_per_dir": files_per_dir,
        "probes": probes, "batch": batch, "n_shards": n_shards,
        "zipf_s": zipf_s,
    }
    try:
        paths = [
            f"/b/d{i:03d}/f{j:04d}"
            for i in range(n_dirs)
            for j in range(files_per_dir)
        ]
        dirs = sorted({p.rsplit("/", 1)[0] for p in paths})
        # even initial bounds from the REAL directory keyspace, so the
        # 4-shard leg measures parallelism, not a lucky/unlucky hash
        bounds = [
            dirs[len(dirs) * (i + 1) // n_shards]
            for i in range(n_shards - 1)
        ]

        single = SqliteFilerStore(os.path.join(d, "single.db"))
        sharded = ShardedFilerStore(
            os.path.join(d, "shards"),
            lambda name: SqliteFilerStore(
                os.path.join(d, "shards", name + ".db")
            ),
            n_shards=n_shards,
            initial_bounds=bounds,
        )
        for store in (single, sharded):
            store.insert_entry(new_directory_entry("/", 0o775))
            store.insert_entry(new_directory_entry("/b"))
            for dirp in dirs:
                store.insert_entry(new_directory_entry(dirp))
            for p in paths:
                store.insert_entry(
                    Entry(
                        full_path=p,
                        attr=Attr(mtime=1.0, crtime=1.0),
                        extended={"etag": p[-8:]},
                    )
                )

        zipf = ZipfKeys(n=len(paths), s=zipf_s, seed=7, cold_fraction=0.05)
        out["hot_share_top1pct"] = round(zipf.hot_share(0.01), 4)
        idxs = zipf.draw(probes)
        probe_paths = [paths[i] for i in idxs.tolist()]

        # entry identity on a sample (and page warmup for both stores)
        sample = probe_paths[: min(probes, 4000)]
        got_sharded = sharded.find_many(sample)
        mismatches = 0
        for p in sample:
            a = single.find_entry(p)
            b = got_sharded.get(p)
            if a is None or b is None or a.to_dict() != b.to_dict():
                mismatches += 1
        out["identical"] = mismatches == 0
        out["probe_mismatches"] = mismatches

        def run_single_seq() -> dict:
            svc = LogHistogram()
            find = single.find_entry
            now = time.perf_counter
            t0 = now()
            for p in probe_paths:
                t = now()
                find(p)
                svc.record(now() - t)
            wall = now() - t0
            s = svc.summary_ms()
            return {
                "qps": round(probes / wall),
                "p50_us": round(s["p50_ms"] * 1e3, 2),
                "p99_us": round(s["p99_ms"] * 1e3, 2),
                "store_calls_per_probe": 1.0,
            }

        def run_batched(store) -> dict:
            svc = LogHistogram()  # amortized per-probe service time
            fm = store.find_many
            # snapshot so the disclosure is per-RUN scanned work, not a
            # cumulative count inflated by warmup + earlier reps
            base_calls = (
                store.stats["batches"] if hasattr(store, "stats") else None
            )
            now = time.perf_counter
            t0 = now()
            for i in range(0, probes, batch):
                group = probe_paths[i : i + batch]
                t = now()
                fm(group)
                dt = now() - t
                per = dt / len(group)
                for _ in group:
                    svc.record(per)
            wall = now() - t0
            s = svc.summary_ms()
            calls = (
                store.stats["batches"] - base_calls
                if base_calls is not None
                else (probes + batch - 1) // batch
            )
            return {
                "qps": round(probes / wall),
                "p50_us": round(s["p50_ms"] * 1e3, 2),
                "p99_us": round(s["p99_ms"] * 1e3, 2),
                "store_calls_per_probe": round(calls / probes, 4),
            }

        legs = {
            "single_seq": (run_single_seq,),
            "single_batched": (run_batched, single),
            "sharded_batched": (run_batched, sharded),
        }
        best: dict = {name: None for name in legs}
        for rep in range(reps):
            order = list(legs.items())
            if rep % 2:
                order.reverse()  # interleave against shared-host noise
            for name, spec in order:
                r = spec[0](*spec[1:])
                if best[name] is None or r["qps"] > best[name]["qps"]:
                    best[name] = r
        out.update(best)
        out["qps_ratio_sharded_over_single"] = round(
            best["sharded_batched"]["qps"]
            / max(best["single_seq"]["qps"], 1),
            2,
        )
        out["qps_ratio_batching_only"] = round(
            best["single_batched"]["qps"]
            / max(best["single_seq"]["qps"], 1),
            2,
        )
        out["sharded_stats"] = dict(sharded.stats)
        sharded.close()
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_meta_feed(
    n_subscribers: int = 4,
    events: int = 4000,
    segment_events: int = 512,
    ring_capacity: int = 256,
) -> dict:
    """meta.feed leg (ISSUE 15): N subscribers replaying the durable
    meta-log change feed concurrently while a writer appends. The ring
    capacity is set far below the event count ON PURPOSE: every
    subscriber starts cold, so the replay crosses the segment/ring
    boundary and segment rotation mid-stream. Disclosed: append
    throughput, per-subscriber delivery lag p99 (append->receipt wall),
    exactness (every subscriber sees exactly the appended sequence),
    and a kill/resume probe — one subscriber stops mid-stream, acks a
    durable cursor, and a fresh subscription resumes with zero missed
    or duplicated events."""
    import asyncio
    import shutil
    import tempfile

    from seaweedfs_tpu.filer.meta_log import DurableMetaLog
    from seaweedfs_tpu.ops.loadgen import LogHistogram

    use_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    d = tempfile.mkdtemp(prefix="bench_meta_feed_", dir=use_dir)
    out: dict = {
        "n_subscribers": n_subscribers, "events": events,
        "segment_events": segment_events, "ring_capacity": ring_capacity,
    }

    async def body() -> None:
        log = DurableMetaLog(
            d, capacity=ring_capacity, segment_events=segment_events,
            max_segments=1024,
        )
        appended: list[int] = []
        append_wall = [0.0]

        async def writer():
            t0 = time.perf_counter()
            for i in range(events):
                ev = log.append(
                    "/feed",
                    "create",
                    None,
                    {"full_path": f"/feed/k{i:06d}", "name": f"k{i:06d}"},
                )
                appended.append(ev.ts_ns)
                if i % 97 == 0:
                    await asyncio.sleep(0)  # let subscribers drain
            append_wall[0] = time.perf_counter() - t0

        lags = [LogHistogram() for _ in range(n_subscribers)]
        seen: list[list[int]] = [[] for _ in range(n_subscribers)]

        async def subscriber(si: int):
            async for ev in log.subscribe(0, "/feed", poll_interval=0.002):
                seen[si].append(ev.ts_ns)
                lags[si].record(
                    max(0.0, time.time_ns() - ev.ts_ns) / 1e9
                )
                if len(seen[si]) >= events:
                    return

        t0 = time.perf_counter()
        await asyncio.gather(
            writer(), *(subscriber(i) for i in range(n_subscribers))
        )
        wall = time.perf_counter() - t0
        out["append_events_per_s"] = round(events / append_wall[0])
        out["e2e_events_per_s"] = round(events / wall)
        out["exact"] = all(s == appended for s in seen)
        lag_p99s = [h.summary_ms()["p99_ms"] for h in lags]
        out["lag_p99_ms"] = round(max(lag_p99s), 3)
        out["lag_p99_ms_per_subscriber"] = [
            round(x, 3) for x in lag_p99s
        ]
        out["segments"] = len(log._segments)

        # kill/resume probe: consume a third, ack the cursor, die;
        # resume from the durable cursor in a FRESH log handle (the
        # restart shape) and take the rest — union must be exact
        name = "bench-resume"
        first: list[int] = []
        async for ev in log.subscribe(0, "/feed", poll_interval=0.002):
            first.append(ev.ts_ns)
            log.cursor_ack(name, ev.ts_ns)
            if len(first) >= events // 3:
                break
        log.close()
        log2 = DurableMetaLog(
            d, capacity=ring_capacity, segment_events=segment_events,
            max_segments=1024,
        )
        cursor = log2.cursor_load(name)
        rest: list[int] = []
        async for ev in log2.subscribe(
            cursor, "/feed", poll_interval=0.002
        ):
            rest.append(ev.ts_ns)
            if len(rest) >= events - len(first):
                break
        out["resume_exact"] = (first + rest) == appended
        out["resume_missed"] = len(set(appended) - set(first + rest))
        out["resume_duplicated"] = len(first + rest) - len(
            set(first + rest)
        )
        log2.close()

    try:
        asyncio.run(body())
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def measure_meta_fleet(
    n_dirs: int = 48,
    files_per_dir: int = 25,
    lookups: int = 8000,
    lists: int = 1600,
    fleet_sizes: tuple = (1, 2, 4),
    drivers: int = 4,
    concurrency: int = 24,
    put_burst: int = 1000,
    seed: int = 11,
    driver_timeout_s: float = 120.0,
) -> dict:
    """meta.fleet leg (ISSUE 20 tentpole): lookup/LIST QPS of a
    shard-range filer FLEET vs process count, plus the gate-batched
    write seam's store-round economics — all over REAL processes.

    For each N in `fleet_sizes` a ProcCluster spawns master + N filer
    members routed by a pre-written FLEETMAP whose bounds split the
    REAL directory keyspace evenly; the namespace is preloaded through
    routed CreateEntry RPCs, then `drivers` out-of-process load drivers
    (ops/meta_fleet_driver — separate OS processes, so the client GIL
    can never cap the fleet) probe uniform-random lookups and LISTs
    with per-answer identity checks (expected etag / expected entry
    count) under a filesystem go-signal so walls cover probing only.

    Fleet QPS is the SUM of per-member capacities, each member driven
    alone over its own range slice — the one-core-per-process
    deployment model, which a credit-window CI host (often 1 core)
    cannot express as concurrent wall clock. The sum is additive
    because the hot path is coordination-free, and that is PROVEN per
    run: every member's `forwarded` counter must stay 0 across all
    probes (`coordination_free`). Concurrent same-host walls,
    `cpu_count`, and driver error/mismatch counts (must be zero) are
    all disclosed.

    The write seam is scored on the SAME 1k-object concurrent PUT
    burst against two single-filer clusters — write gate on vs off —
    by the store's own write_rounds counter (one round = one lock
    acquisition / sqlite commit / WAL fsync): the disclosed ratio is
    rounds(per-entry)/rounds(gated), the O(objects) -> O(wakeups)
    claim measured end to end through real gRPC."""
    import asyncio
    import shutil
    import subprocess
    import tempfile

    from seaweedfs_tpu.filer.entry import Attr, Entry
    from seaweedfs_tpu.ops.proc_cluster import ProcCluster
    from seaweedfs_tpu.pb import grpc_address
    from seaweedfs_tpu.pb.rpc import Stub, new_channel

    def _stub(addr: str) -> tuple:
        # private channel per asyncio.run block: the process-wide cached
        # channel would outlive its loop and poison the next block
        ch = new_channel(grpc_address(addr))
        return Stub(grpc_address(addr), "filer", channel=ch), ch

    rng = np.random.default_rng(seed)
    use_dir = "/dev/shm" if os.path.isdir("/dev/shm") else None
    d = tempfile.mkdtemp(prefix="bench_meta_fleet_", dir=use_dir)
    out: dict = {
        "n_dirs": n_dirs, "files_per_dir": files_per_dir,
        "lookups": lookups, "lists": lists,
        "fleet_sizes": list(fleet_sizes), "drivers": drivers,
        "concurrency": concurrency, "put_burst": put_burst,
    }
    dirs = [f"/b/d{i:03d}" for i in range(n_dirs)]
    paths = [f"{dp}/f{j:04d}" for dp in dirs for j in range(files_per_dir)]
    etag = {p: p[-9:] for p in paths}

    def entry_dict(p: str) -> dict:
        return Entry(
            full_path=p,
            attr=Attr(mtime=1.0, crtime=1.0),
            extended={"etag": etag[p]},
        ).to_dict()

    def bounds_for(n: int) -> list:
        # even split points from the REAL directory keyspace, so the
        # leg measures process parallelism, not a lucky hash
        return [dirs[len(dirs) * (i + 1) // n] for i in range(n - 1)]

    async def preload(addresses: list, bounds: list) -> None:
        import bisect as _bisect

        pairs = [_stub(a) for a in addresses]
        sem = asyncio.Semaphore(64)

        async def put(p: str) -> None:
            async with sem:
                stub = pairs[_bisect.bisect_right(
                    bounds, p.rsplit("/", 1)[0]
                )][0]
                r = await stub.call(
                    "CreateEntry", {"entry": entry_dict(p)}, timeout=30.0
                )
                if r.get("error"):
                    raise RuntimeError(f"preload {p}: {r['error']}")

        try:
            await asyncio.gather(*(put(p) for p in paths))
        finally:
            for _, ch in pairs:
                await ch.close()

    def run_drivers(kind: str, items: list, addresses: list,
                    bounds: list, tag: str) -> dict:
        go = os.path.join(d, f"go-{tag}")
        procs = []
        share = (len(items) + drivers - 1) // drivers
        for k in range(drivers):
            spec = {
                "kind": kind, "addresses": addresses, "bounds": bounds,
                "items": items[k * share : (k + 1) * share],
                "concurrency": concurrency, "go_file": go,
            }
            p = subprocess.Popen(
                [sys.executable, "-m",
                 "seaweedfs_tpu.ops.meta_fleet_driver"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
            )
            p.stdin.write(json.dumps(spec).encode())
            p.stdin.close()
            procs.append(p)
        # every driver parses + connects before ANY starts probing
        deadline = time.monotonic() + driver_timeout_s
        while time.monotonic() < deadline:
            ready = [
                f for f in os.listdir(d)
                if f.startswith(f"go-{tag}.ready.")
            ]
            if len(ready) >= drivers:
                break
            if any(p.poll() is not None for p in procs):
                break
            time.sleep(0.01)
        open(go, "w").close()
        n = errors = mismatches = 0
        wall = 0.0
        for p in procs:
            try:
                p.wait(timeout=driver_timeout_s)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
            raw = p.stdout.read()
            err = p.stderr.read()
            if p.returncode != 0:
                raise RuntimeError(
                    f"fleet driver rc={p.returncode}: "
                    f"{err.decode('utf-8', 'replace')[-400:]}"
                )
            r = json.loads(raw)
            n += r["n"]
            errors += r["errors"]
            mismatches += r["mismatches"]
            wall = max(wall, r["wall_s"])
        return {
            "qps": round(n / max(wall, 1e-9)),
            "n": n, "errors": errors, "mismatches": mismatches,
            "wall_s": round(wall, 3),
        }

    async def fleet_status(addr: str) -> dict:
        stub, ch = _stub(addr)
        try:
            return await stub.call("FleetStatus", {}, timeout=10.0)
        finally:
            await ch.close()

    try:
        import bisect as _bisect

        # Scaling methodology on a credit-window CI host: fleet
        # capacity is the SUM of per-member capacities, each measured
        # with that member driven alone — the one-core-per-process
        # deployment model (this host has os.cpu_count() cores; with
        # fewer cores than members, concurrent wall-clock QPS is bound
        # by the host, not the architecture). The sum is additive
        # because ranges are disjoint and the hot path is
        # coordination-free — PROVEN per run, not assumed: every
        # member's `forwarded` counter must stay 0 across all probes
        # (coordination_free below). Concurrent same-host numbers are
        # disclosed alongside, never hidden.
        per_n: dict = {}
        for n in fleet_sizes:
            root = os.path.join(d, f"fleet{n}")
            bounds = bounds_for(n)
            with ProcCluster(
                root, volumes=0, filers=n,
                fleet=True, fleet_bounds=bounds,
            ) as cluster:
                addresses = [
                    cluster.address(f"filer-{i}") for i in range(n)
                ]
                t0 = time.perf_counter()
                asyncio.run(preload(addresses, bounds))
                preload_s = time.perf_counter() - t0
                li = rng.integers(0, len(paths), size=lookups)
                lookup_items = [
                    {
                        "directory": paths[i].rsplit("/", 1)[0],
                        "name": paths[i].rsplit("/", 1)[1],
                        "etag": etag[paths[i]],
                    }
                    for i in li.tolist()
                ]
                di = rng.integers(0, len(dirs), size=lists)
                list_items = [
                    {"directory": dirs[i], "count": files_per_dir}
                    for i in di.tolist()
                ]
                member_lk, member_ls = [], []
                for i, addr in enumerate(addresses):
                    mine_lk = [
                        it for it in lookup_items
                        if _bisect.bisect_right(
                            bounds, it["directory"]
                        ) == i
                    ]
                    mine_ls = [
                        it for it in list_items
                        if _bisect.bisect_right(
                            bounds, it["directory"]
                        ) == i
                    ]
                    member_lk.append(run_drivers(
                        "lookup", mine_lk, [addr], [],
                        f"cap-lk{n}-{i}",
                    ))
                    member_ls.append(run_drivers(
                        "list", mine_ls, [addr], [], f"cap-ls{n}-{i}"
                    ))
                con_lk = run_drivers(
                    "lookup", lookup_items, addresses, bounds,
                    f"con-lk{n}",
                )
                con_ls = run_drivers(
                    "list", list_items, addresses, bounds, f"con-ls{n}"
                )
                statuses = [
                    asyncio.run(fleet_status(a)) for a in addresses
                ]
                forwarded = sum(
                    s["fleet"]["counters"]["forwarded"]
                    for s in statuses
                )
                per_n[str(n)] = {
                    "lookup_capacity_qps": sum(
                        m["qps"] for m in member_lk
                    ),
                    "list_capacity_qps": sum(
                        m["qps"] for m in member_ls
                    ),
                    "per_member_lookup": member_lk,
                    "per_member_list": member_ls,
                    "concurrent_lookup": con_lk,
                    "concurrent_list": con_ls,
                    "forwarded_during_probes": forwarded,
                    "preload_s": round(preload_s, 3),
                    "member0_write_gate": statuses[0].get("write_gate"),
                }
        out["per_fleet_size"] = per_n
        out["cpu_count"] = os.cpu_count()
        lo = str(fleet_sizes[0])
        hi = str(fleet_sizes[-1])
        out["lookup_qps_scaling"] = round(
            per_n[hi]["lookup_capacity_qps"]
            / max(per_n[lo]["lookup_capacity_qps"], 1),
            2,
        )
        out["list_qps_scaling"] = round(
            per_n[hi]["list_capacity_qps"]
            / max(per_n[lo]["list_capacity_qps"], 1),
            2,
        )
        out["concurrent_lookup_scaling"] = round(
            per_n[hi]["concurrent_lookup"]["qps"]
            / max(per_n[lo]["concurrent_lookup"]["qps"], 1),
            2,
        )
        out["coordination_free"] = all(
            v["forwarded_during_probes"] == 0 for v in per_n.values()
        )
        runs = [
            m
            for v in per_n.values()
            for m in (
                v["per_member_lookup"] + v["per_member_list"]
                + [v["concurrent_lookup"], v["concurrent_list"]]
            )
        ]
        out["identical"] = all(
            m["mismatches"] == 0 and m["errors"] == 0 for m in runs
        )

        # ---- the write seam: same burst, gate on vs gate off ----
        burst_paths = [
            f"/w/burst/o{i:04d}" for i in range(put_burst)
        ]
        rounds: dict = {}
        for gate in ("1", "0"):
            root = os.path.join(d, f"burst-gate{gate}")
            with ProcCluster(
                root, volumes=0, filers=1,
                env={"SEAWEEDFS_TPU_META_WRITE_GATE": gate},
            ) as cluster:
                addr = cluster.address("filer-0")

                async def burst() -> tuple:
                    stub, ch = _stub(addr)
                    r0 = await stub.call("FleetStatus", {}, timeout=10.0)
                    t0 = time.perf_counter()
                    resps = await asyncio.gather(*(
                        stub.call(
                            "CreateEntry",
                            {"entry": {
                                "full_path": p,
                                "attr": {"mtime": 1.0, "crtime": 1.0},
                                "extended": {"etag": p[-9:]},
                            }},
                            timeout=60.0,
                        )
                        for p in burst_paths
                    ))
                    wall = time.perf_counter() - t0
                    bad = [r for r in resps if r.get("error")]
                    if bad:
                        raise RuntimeError(f"burst failed: {bad[0]}")
                    # identity: every object must land readable
                    import random as _random

                    _random.seed(seed)
                    for p in _random.sample(burst_paths, 50):
                        d_, name = p.rsplit("/", 1)
                        rr = await stub.call(
                            "LookupDirectoryEntry",
                            {"directory": d_, "name": name},
                            timeout=10.0,
                        )
                        e = rr.get("entry")
                        if (
                            e is None
                            or (e.get("extended") or {}).get("etag")
                            != p[-9:]
                        ):
                            raise RuntimeError(
                                f"burst identity check failed at {p}"
                            )
                    r1 = await stub.call("FleetStatus", {}, timeout=10.0)
                    await ch.close()
                    return (
                        r1["write_rounds"] - r0["write_rounds"],
                        wall,
                        r1.get("write_gate"),
                    )

                delta, wall, gs = asyncio.run(burst())
                rounds[gate] = {
                    "write_rounds": delta,
                    "wall_s": round(wall, 3),
                    "puts_per_s": round(put_burst / max(wall, 1e-9)),
                    "write_gate": gs,
                }
        out["burst_gated"] = rounds["1"]
        out["burst_per_entry"] = rounds["0"]
        out["write_rounds_ratio"] = round(
            rounds["0"]["write_rounds"]
            / max(rounds["1"]["write_rounds"], 1),
            1,
        )
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def main() -> None:
    from seaweedfs_tpu.ops.gf256 import pack_bytes_host
    from seaweedfs_tpu.storage.erasure_coding.coder_cpu import CpuRSCodec
    from seaweedfs_tpu.tpu.coder import get_codec
    from seaweedfs_tpu.util import available_cpus

    # global wall-clock budget: a driver-side kill before the final print
    # would lose EVERY number, so each secondary metric checks the budget
    # and is skipped (recorded as such) once it runs out
    t_start = time.perf_counter()
    budget = float(os.environ.get("BENCH_TOTAL_BUDGET", 900))

    def remaining() -> float:
        return budget - (time.perf_counter() - t_start)

    extra: list = []
    # ONE headline record, mutated in place as legs complete and shared
    # with the watchdog and the __main__ crash handler: a tunnel that dies
    # MID-run either hangs the in-flight jax call forever (uninterruptible
    # — the watchdog emits and hard-exits) or raises (the crash handler
    # emits), so the driver-visible artifact survives both r4 failure
    # modes with whatever has been measured.
    global _LAST_HEADLINE
    partial = _LAST_HEADLINE = {
        "metric": "ec.encode_throughput",
        "value": None,
        "unit": "GB/s",
        "vs_baseline": None,
        "device_status": "unknown",
        "extra": extra,
    }
    if os.environ.get("GRAFT_BENCH_CPU_FALLBACK"):
        partial["note"] = (
            "DEVICE UNREACHABLE this run (tunnel/relay down at bench "
            "time): device legs measured on the pure-CPU stand-in; "
            "host-side metrics (serving, e2e, host_kernel, multi) are "
            "unaffected"
        )
    _arm_watchdog(budget + 150.0, partial)

    codec = CpuRSCodec()
    rng = np.random.default_rng(0)

    # CPU baseline: reference-equivalent (PSHUFB-tier) SIMD single-thread
    # on a 40MB stripe batch — see baseline_mat_apply
    baseline_codec = _BaselineCodecShim(codec.parity_matrix)
    cpu_data = rng.integers(0, 256, size=(10, 4 << 20), dtype=np.uint8)
    cpu_gbps = measure_cpu_baseline(baseline_codec, cpu_data)

    # TPU on a 160MB HBM-resident stripe batch
    data = rng.integers(0, 256, size=(10, 16 << 20), dtype=np.uint8)
    packed = pack_bytes_host(data)
    tpu_gbps = measure_tpu(codec.parity_matrix, packed)
    partial["value"] = round(tpu_gbps, 3)
    partial["vs_baseline"] = round(tpu_gbps / cpu_gbps, 2)
    partial["device_status"] = _device_status()

    def budgeted(metric: str, min_seconds: float) -> bool:
        if remaining() < min_seconds:
            extra.append({"metric": metric, "skipped": "bench budget spent"})
            return False
        return True

    try:
        if not budgeted("kernel_roofline", 90):
            raise _Skip()
        roof = measure_kernel_roofline(codec.parity_matrix, packed)
        extra.append(
            {
                "metric": "kernel_roofline",
                "value": roof.get(roof.get("best_mode"), {}).get("gbps"),
                "unit": "GB/s",
                "vs_baseline": roof.get("mul_vs_shift"),
                "detail": roof,
                "note": "measured i32 ops/s vs nominal VPU peak and HBM "
                "traffic vs nominal HBM peak for both xtime formulations "
                "(VERDICT r4 item 5); vs_baseline = mul-formulation "
                "speedup over the r4 shift formulation; bottleneck stated "
                "in detail",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "kernel_roofline", "error": str(e)[:200]})

    try:
        if not budgeted("kernel_mxu_bitslice", 60):
            raise _Skip()
        # the identity check runs on EVERY backend (the bitplane
        # formulation is backend-agnostic): a formulation regression must
        # surface even while the relay is down (ISSUE 17)
        status = _device_status()
        try:
            ident = measure_mxu_bitslice_identity()
        except Exception as ie:
            ident = {"error": str(ie)[:200], "all_identical": False}
        if status != "tpu":
            # there is no MXU on the CPU stand-in: a throughput number
            # here answers nothing and eats budget real metrics need —
            # but the skip is DISCLOSED, never silent, and carries the
            # identity verdict from this backend
            extra.append(
                {
                    "metric": "kernel_mxu_bitslice",
                    "skipped": "no MXU on CPU stand-in (device_status="
                    f"{status}): throughput not scored; bit-slice "
                    "formulation identity-checked vs the table codec on "
                    "this backend instead",
                    "device_status": status,
                    "identity_vs_table_codec": ident,
                }
            )
            raise _Skip()
        mx = measure_mxu_bitslice(codec.parity_matrix, packed)
        extra.append(
            {
                "metric": "kernel_mxu_bitslice",
                "value": mx["bitslice_gbps"],
                "unit": "GB/s",
                "vs_baseline": mx["vs_packed"],
                "device_status": status,
                "identity_vs_table_codec": ident,
                "detail": mx,
                "note": "MXU bit-slice prototype (GF(2) matmul over bit "
                "planes, ops/gf256.gf_matmul_bitsliced) vs the shipping "
                "packed VPU kernel on the same HBM-resident batch "
                "(VERDICT r4 item 5's in-tree prototype + measurement); "
                "meaningful only when device_status=tpu",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "kernel_mxu_bitslice", "error": str(e)[:200]})

    try:
        # promoted from optional to benched (ISSUE 17): the mesh legs run
        # every bench, encode AND rebuild, with device_status disclosed
        if not budgeted("ec.encode.sharded", 90):
            raise _Skip()
        extra.extend(_run_sharded_timeboxed())
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "ec.encode.sharded", "error": str(e)[:200]})

    try:
        if not budgeted("ec.encode.host_kernel", 15):
            raise _Skip()
        # shipping host codec (GFNI tier where the CPU has it) vs the
        # reference-equivalent PSHUFB tier — the host-side technique win
        from seaweedfs_tpu import native as _native

        tier = (
            "GFNI VGF2P8AFFINEQB tier"
            if _native.encode_copy_available()
            else "PSHUFB tier (no GFNI on this host)"
        )
        host_gbps = measure_cpu_baseline(get_codec("cpu"), cpu_data)
        extra.append(
            {
                "metric": "ec.encode.host_kernel",
                "value": round(host_gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(host_gbps / cpu_gbps, 2),
                "note": f"single-thread host codec ({tier}) vs the "
                "PSHUFB-tier baseline (the reference's vendored "
                "reedsolomon v1.9.2 technique)",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "ec.encode.host_kernel", "error": str(e)[:200]})

    try:
        lookup_qps, lookup_cpu_qps = measure_lookup()
        extra.append(
            {
                "metric": "needle_lookup_qps",
                "value": round(lookup_qps),
                "unit": "probes/s",
                "vs_baseline": round(lookup_qps / lookup_cpu_qps, 2),
            }
        )
    except Exception as e:  # never lose the headline metric to a new bench
        extra.append({"metric": "needle_lookup_qps", "error": str(e)[:200]})

    try:
        if not budgeted("needle_map.device_lookup", 150):
            raise _Skip()
        dl = measure_needle_map_device_lookup()
        entry = {
            "metric": "needle_map.device_lookup",
            "value": dl["device_gate"]["probes_per_s"],
            "unit": "#/sec",
            "vs_baseline": round(
                dl["device_gate"]["probes_per_s"]
                / max(1, dl["host_gate"]["probes_per_s"]),
                3,
            ),
            "detail": dl,
            "device_status": dl["device_status"],
            "stage_breakdown": dl["kernel"]["stage_breakdown"],
            "coverage_of_wall": dl["kernel"]["stage_breakdown"][
                "coverage_of_wall"
            ],
            "identity_ok": dl["identity"]["ok"],
            "valid": dl["valid"],
            "note": "MEASURED ragged device lookups through the real "
            "gate seam (supersedes lookup_gate.decomposition's "
            "projection): arena-backed gate vs host gate in the same "
            "credit window at the gate's own scraped batch-size "
            "distribution, entry-wise identity asserted on every "
            "dispatch; " + dl["note"],
        }
        extra.append(entry)
    except _Skip:
        pass
    except Exception as e:
        extra.append(
            {"metric": "needle_map.device_lookup", "error": str(e)[:200]}
        )

    try:
        if not budgeted("ec.rebuild_throughput", 90):
            raise _Skip()
        rb = measure_rebuild_e2e()
        extra.append(
            {
                "metric": "ec.rebuild_throughput",
                "value": rb.get("best_gbps"),
                "unit": "GB/s",
                # vs the pre-fast-path structure: synchronous loop, all-rows
                # reconstruct per chunk, same codec and files
                "vs_baseline": round(
                    rb.get("best_gbps", 0) / max(rb.get("ref_gbps", 1e-9), 1e-9),
                    2,
                ),
                "detail": rb,
                "note": "END-TO-END rebuild of 4 lost shards through "
                "rebuild_ec_files (survivor reads + missing-rows-only "
                "decode + shard writes), GB/s over survivor bytes read "
                "(10 x shard size ~= .dat bytes, the kernel metric's "
                "basis); vs_baseline = the shipping pipelined fast path "
                "over the previous synchronous all-rows structure on the "
                "same files; detail.stages is the per-stage breakdown "
                "(pipelined stages overlap). The raw kernel-level number "
                "is ec.rebuild_throughput.kernel",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "ec.rebuild_throughput", "error": str(e)[:200]})

    try:
        if not budgeted("ec.rebuild_throughput.kernel", 45):
            raise _Skip()
        rb_tpu, rb_cpu = measure_rebuild()
        extra.append(
            {
                "metric": "ec.rebuild_throughput.kernel",
                "value": round(rb_tpu, 3),
                "unit": "GB/s",
                "vs_baseline": round(rb_tpu / rb_cpu, 2),
                "note": "device decode matmul alone (BASELINE config 2's "
                "kernel leg; r05's headline rebuild number) vs the "
                "PSHUFB-tier host baseline — the e2e repair-plane number "
                "is ec.rebuild_throughput",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append(
            {"metric": "ec.rebuild_throughput.kernel", "error": str(e)[:200]}
        )

    try:
        if not budgeted("vacuum.throughput", 40):
            raise _Skip()
        vt = measure_vacuum_throughput()
        extra.append(
            {
                "metric": "vacuum.throughput",
                "value": vt.get("best_gbps"),
                "unit": "GB/s",
                # vs the retained needle-at-a-time reference loop on the
                # same half-garbage volume (acceptance: >= 5x)
                "vs_baseline": vt.get("vs_naive"),
                "detail": vt,
                "note": "extent-coalesced compaction through "
                "vacuum._copy_data_based_on_index_file (offset-ordered "
                "live walk, adjacent records coalesced into multi-MB "
                "extents, raw-byte moves via the measured-race route, "
                "key-sorted .cpx in one vectorized pass), GB/s over live "
                "bytes moved; vs_baseline = fast path over the retained "
                "naive pread+parse+reserialize loop (vacuum._copy_naive); "
                "detail.stages is the per-stage breakdown (pipelined read "
                "overlaps write), detail.route the race winner, "
                "detail.identical the per-record content-identity check "
                "between the two shadow sets",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "vacuum.throughput", "error": str(e)[:200]})

    try:
        if not budgeted("needle_map.mount", 90):
            raise _Skip()
        nmm = measure_needle_map_mount()
        extra.append(
            {
                "metric": "needle_map.mount",
                "value": nmm["mount_speedup"],
                "unit": "x (dict-replay wall / lsm snapshot+tail wall)",
                "vs_baseline": nmm["mount_speedup"],
                "detail": nmm,
                "note": "ISSUE 13 tentpole: mount of a "
                f"{nmm['n_keys'] // 1_000_000}M-needle volume's index — "
                "per-entry dict replay (the memory kind) vs the lsm "
                "map's persisted-snapshot mmap + O(tail) replay "
                f"({nmm['tail_replayed']} tail entries here); resident "
                "bytes are tracemalloc'd Python-allocator deltas (lsm "
                "runs are mmap'd page cache ON PURPOSE — that IS the "
                "memory story), probe sample byte-identical; "
                "mount_lsm_cold_s is the one-time vectorized rebuild a "
                "volume pays to enter the O(tail) regime",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "needle_map.mount", "error": str(e)[:200]})

    try:
        if not budgeted("needle_map.lookup", 60):
            raise _Skip()
        nml = measure_needle_map_lookup()
        extra.append(
            {
                "metric": "needle_map.lookup",
                "value": nml["p99_ratio_lsm_over_dict"],
                "unit": "x (lsm p99 / dict p99, open-loop zipf)",
                "vs_baseline": nml["p99_ratio_lsm_over_dict"],
                "detail": nml,
                "note": "ISSUE 13 read-path flatness: the same "
                "zipf(1.1) open-loop probe stream against the dict map "
                "and the sealed lsm map (one mmap'd sorted run, binary "
                "search per probe), answers asserted identical "
                "entry-wise; scored on per-op SERVICE p99 (CO-corrected "
                "arrival percentiles disclosed in detail — on this "
                "burst-throttled host one CPU-steal stall taints "
                "hundreds of arrival latencies, a lottery for a "
                "data-structure comparison); the ratio is the whole "
                "cost of out-of-core — single-digit µs under a ~35µs "
                "serving request wall",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "needle_map.lookup", "error": str(e)[:200]})

    try:
        if not budgeted("meta.lookup_qps", 60):
            raise _Skip()
        ml = measure_meta_lookup_qps()
        extra.append(
            {
                "metric": "meta.lookup_qps",
                "value": ml["qps_ratio_sharded_over_single"],
                "unit": "x (sharded+gated qps / single-store qps)",
                "vs_baseline": ml["qps_ratio_sharded_over_single"],
                "detail": ml,
                "note": "ISSUE 15 tentpole: the same zipf path-probe "
                "stream against one sqlite filer store probed "
                "per-request (the old metadata plane) vs the "
                "4-shard prefix-sharded store probed through "
                "gate-sized find_many batches, answers asserted "
                "entry-identical on a sample; single_batched is "
                "disclosed so the batching and sharding gains "
                "separate, store_calls_per_probe is the scanned-work "
                "disclosure; all legs interleave in one credit "
                "window, best-of-reps",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "meta.lookup_qps", "error": str(e)[:200]})

    try:
        if not budgeted("meta.feed", 45):
            raise _Skip()
        mf = measure_meta_feed()
        extra.append(
            {
                "metric": "meta.feed",
                "value": mf["lag_p99_ms"],
                "unit": "ms (worst subscriber delivery-lag p99)",
                "detail": mf,
                "note": "ISSUE 15 tentpole: N subscribers replaying "
                "the durable segmented meta-log concurrently while "
                "the writer appends (ring capacity deliberately far "
                "below the event count, so every replay crosses the "
                "segment/ring boundary and mid-stream rotation); "
                "exactness asserted per subscriber, plus a "
                "kill/ack/resume probe through a fresh log handle "
                "with zero missed/duplicated events",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "meta.feed", "error": str(e)[:200]})

    try:
        if not budgeted("meta.fleet", 240):
            raise _Skip()
        mfl = measure_meta_fleet()
        extra.append(
            {
                "metric": "meta.fleet",
                "value": mfl["lookup_qps_scaling"],
                "unit": "x (lookup capacity qps, 4-filer fleet / 1 "
                "filer)",
                "vs_baseline": mfl["lookup_qps_scaling"],
                "detail": mfl,
                "note": "ISSUE 20 tentpole: lookup/LIST QPS against "
                "REAL filer processes routed by a shard-range "
                "FLEETMAP, driven by out-of-process load drivers "
                "(client GIL can't cap the fleet) with per-answer "
                "identity checks; fleet capacity = sum of per-member "
                "capacities (members driven one at a time — the "
                "one-core-per-process model a 1-core CI host can't "
                "run concurrently), additive ONLY because the "
                "forwarded counter proves zero cross-member "
                "coordination; concurrent same-host walls and "
                "cpu_count disclosed in detail; plus the gate-batched "
                "write seam scored by the store's own write_rounds "
                "counter on an identical 1k concurrent PUT burst, "
                "gate on vs off (write_rounds_ratio = "
                "per-entry/gated rounds)",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "meta.fleet", "error": str(e)[:200]})

    try:
        if not budgeted("ec.degraded_read", 30):
            raise _Skip()
        dg = measure_degraded_read()
        extra.append(
            {
                "metric": "ec.degraded_read",
                "value": dg["cold_p50_ms"],
                "unit": "ms (cold p50)",
                "vs_baseline": dg["speedup"],
                "detail": dg,
                "note": "in-process cost of serving one 4KB interval of a "
                "dead shard: cold = survivor reads of the 128KiB "
                "readahead span + missing-row decode + cache fill; "
                "vs_baseline = cold/cache-hit speedup for repeat reads "
                "(the degraded-read interval cache's win); RPC legs of "
                "the distributed path come on top",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "ec.degraded_read", "error": str(e)[:200]})

    serving_qps: Optional[dict] = None
    ping_detail: Optional[dict] = None
    try:
        if not budgeted("serving_read_qps", 60):
            raise _Skip()
        # reference scale is n=1M (README.md:483); run the largest shape
        # the remaining budget affords — 100k files ≈ 35s of writes + 4
        # read legs x best-of-3 ≈ 2 min at current rates
        if "BENCH_QPS_FILES" in os.environ:
            nf = int(os.environ["BENCH_QPS_FILES"])
        elif remaining() > 420:
            nf = 100_000
        elif remaining() > 180:
            nf = 20_000
        else:
            nf = 3_000
        qps = measure_serving_qps(num_files=nf)
        serving_qps = qps
        best_read = max(qps.get("read_qps", 0), qps.get("read_qps_batched", 0))
        extra.append(
            {
                "metric": "serving_read_qps",
                "value": best_read,
                "unit": "#/sec",
                # ref `weed benchmark` random reads, README.md:511-518
                "vs_baseline": round(best_read / 47019.38, 3),
                # closed-loop p99 surfaced next to the QPS (ISSUE 6): the
                # open-loop leg publishes p99/p999, so the legs compare
                # across BENCH revisions instead of mean-derived QPS only
                "read_p99_ms": (qps.get("read_latency") or {}).get("p99_ms"),
                "write_qps": qps.get("write_qps"),
                # ref writes 15,708.23 #/sec, README.md:483-492
                "write_vs_baseline": round(
                    (qps.get("write_qps") or 0) / 15708.23, 3
                ),
                "detail": qps,
                "note": "in-process cluster (byte-level fast tier) on "
                f"tmpfs, 1KB x {qps.get('num_files')} files, "
                f"c={qps.get('concurrency')}, host_cpus="
                f"{available_cpus()} "
                "(reference numbers are from a multicore MacBook); "
                "writes lease fids in count=128 assign batches (the "
                "reference benchmark's fid reuse; write_legs itemizes "
                "the p50); read_qps_batched = "
                "BatchLookupGate micro-batched probes; latency blocks "
                "comparable row-for-row with BASELINE.md. At fixed "
                "concurrency p50 ~= c/QPS (closed loop), so a p50 bar "
                "is a QPS bar: 1.5 ms at c=16 means ~10.7k write QPS. "
                "write_samples/read_samples disclose the per-run swing",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "serving_read_qps", "error": str(e)[:200]})

    try:
        if not budgeted("serving_ping_ceiling", 30):
            raise _Skip()
        pc = measure_ping_ceiling()
        ping_detail = pc
        if serving_qps is not None and pc.get("ping_qps"):
            # the acceptance-visible ratio: how close the read data plane
            # runs to the stack's own trivial-200 floor, same c=16 on both
            # sides
            br = max(
                serving_qps.get("read_qps", 0),
                serving_qps.get("read_qps_batched", 0),
            )
            pc["read_over_ping"] = round(br / pc["ping_qps"], 3)
        extra.append(
            {
                "metric": "serving_ping_ceiling",
                "value": pc["ping_qps"],
                "unit": "#/sec",
                "vs_baseline": pc.get("read_over_ping"),
                "detail": pc,
                "note": "the stack's own floor: trivial-200 QPS at c=16 "
                "through the fast tier + pooled protocol client, with a "
                "raw asyncio echo RTT alongside — read/write QPS above "
                "are interpretable as floor + handler/payload work",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append(
            {"metric": "serving_ping_ceiling", "error": str(e)[:200]}
        )

    try:
        if not budgeted("serving.open_loop", 60):
            raise _Skip()
        ol = measure_serving_open_loop(
            num_files=int(os.environ.get("BENCH_OL_FILES", 20000)),
            ping=ping_detail,
        )
        summ = ol.get("open_loop", {})
        extra.append(
            {
                "metric": "serving.open_loop",
                "value": ol.get("achieved_qps"),
                "unit": "#/sec",
                # acceptance-visible ratio: achieved read QPS over the
                # stack's own trivial-200 ceiling (target >= 0.8 at
                # zipf 1.1)
                "vs_baseline": ol.get("achieved_over_ping"),
                "p99_ms": summ.get("p99_ms"),
                "p999_ms": summ.get("p999_ms"),
                "detail": ol,
                "note": "open-loop zipfian read leg (ops/loadgen.py): "
                "Poisson arrivals at the measured serving_ping_ceiling "
                "rate, latency-unbounded, zipf(1.1) keys + 5% uniform "
                "cold scan over a weighted size mix; latency measured "
                "from SCHEDULED arrival (coordinated-omission-corrected "
                "log-bucketed histogram, p50/p99/p999 published); reads "
                "ride the client replica fan-out (round-robin + p99 "
                "hedging) and the volume server's hot-needle cache "
                "(hit rate + byte-identity vs uncached in detail); "
                "brownout sub-leg = util/faults.brownout ramped latency "
                "on the HTTP seam at half rate",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "serving.open_loop", "error": str(e)[:200]})

    try:
        if not budgeted("serving.overload", 60):
            raise _Skip()
        ov = measure_serving_overload(
            num_files=int(os.environ.get("BENCH_OVERLOAD_FILES", 6000)),
        )
        ovl = ov.get("overload", {})
        extra.append(
            {
                "metric": "serving.overload",
                "value": ovl.get("goodput_qps"),
                "unit": "#/sec",
                # acceptance-visible ratio: goodput at 3x offered over
                # the same-construction 1x ceiling (target >= 0.7)
                "vs_baseline": ov.get("goodput_over_ceiling"),
                "admitted_p99_over_ceiling_p99": ov.get(
                    "admitted_p99_over_ceiling_p99"
                ),
                "shed_rtt_p99_ms": (ovl.get("shed_rtt") or {}).get(
                    "p99_ms"
                ),
                "shed_path_us": ov.get("shed_path_us"),
                "detail": ov,
                "note": "overload control plane (ISSUE 9): open-loop "
                "zipf(1.1) reads offered at 3x the same-credit-window "
                "inline trivial-200 ping against one volume server; "
                "value = goodput (completed 200s/s) under 3x offered, "
                "vs_baseline = goodput over the 1x-offered ceiling "
                "sub-leg's goodput (no congestion collapse >= 0.7); "
                "the gate's read queue budget is scaled to 2.5x the "
                "ceiling leg's measured admitted p99, so "
                "admitted_p99_over_ceiling_p99 <= ~3.5 holds by budget "
                "construction and is disclosed as measured; shed_rtt "
                "is the client-observed 503 round trip on the shared "
                "saturated loop, shed_path_us the in-situ cost of the "
                "refusal itself (classify + try_admit + pre-rendered "
                "503 handoff); brownout_recovery sub-leg = ramped "
                "server-seam latency for the first third of a 1x run, "
                "per-second goodput buckets show degrade->heal->"
                "recover; client breakers disabled for the leg (the "
                "generator must keep offering — breaker behavior is "
                "proven in tests/test_overload.py chaos tests)",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "serving.overload", "error": str(e)[:200]})

    try:
        if not budgeted("serving.trace_overhead", 45):
            raise _Skip()
        to = measure_trace_overhead(
            num_files=int(os.environ.get("BENCH_TRACE_FILES", 6000)),
        )
        extra.append(
            {
                "metric": "serving.trace_overhead",
                "value": to.get("qps_on"),
                "unit": "#/sec",
                # acceptance ratio: tracing-on-at-1% over tracing-off in
                # the same credit window (target >= 0.97)
                "vs_baseline": to.get("on_over_off"),
                "qps_off": to.get("qps_off"),
                "admissions_equal_sampled": to.get(
                    "admissions_equal_sampled"
                ),
                "detail": to,
                "note": "ONE continuous open-loop zipf(1.1) read stream "
                "offered at the inline trivial-200 ping rate with the "
                "flight recorder toggled off<->on every ~100ms (value = "
                "achieved QPS in the on-windows at 1% head sampling; "
                "both modes' wall QPS + the macro on/off ratio and its "
                "±15-20% per-window noise disclosed in detail); "
                "vs_baseline = service_us / (service_us + overhead_us) "
                "where overhead_us is the tracing plane's per-request "
                "cost measured in situ (the exact fast-tier block, "
                "sampled spans included) and service_us is the macro "
                "stream's measured per-request service time — the "
                "macro A/B's noise floor on this host is an order of "
                "magnitude above the effect, so the deterministic "
                "construction is the disclosed comparison; "
                "admissions_equal_sampled asserts the zero-alloc "
                "unsampled fast path (ring admissions == sampled roots "
                "+ tail promotions, never one per request)",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append(
            {"metric": "serving.trace_overhead", "error": str(e)[:200]}
        )

    try:
        if not budgeted("s3.put_qps", 90):
            raise _Skip()
        s3g = measure_s3_gateway(
            num_objects=int(os.environ.get("BENCH_S3_OBJECTS", 3000)),
            list_keys=int(os.environ.get("BENCH_S3_LIST_KEYS", 10000)),
        )
        budget_detail = s3g.get("s3_stage_budget", {})
        extra.append(
            {
                "metric": "s3.put_qps",
                "value": s3g.get("put_qps"),
                "unit": "#/sec",
                # acceptance ratio: gateway PutObject vs the raw
                # volume-tier write leg in the SAME credit window
                # (target >= 0.5)
                "vs_baseline": s3g.get("put_vs_raw"),
                "coverage_of_p50": budget_detail.get("coverage_of_p50"),
                "detail": s3g,
                "note": "closed-loop c=16 PutObject through the S3 fast "
                "tier (shared serving core + leased chunk uploads into "
                "the volume fast write tier); vs_baseline = put_qps / "
                "raw_put_qps (direct leased volume PUTs, same window); "
                "detail.s3_stage_budget itemizes the handler wall into "
                "auth/meta/lease/upload/render with coverage_of_p50 "
                "(serving_write_budget methodology)",
            }
        )
        extra.append(
            {
                "metric": "s3.get_qps",
                "value": s3g.get("get_qps"),
                "unit": "#/sec",
                "vs_baseline": s3g.get("get_vs_raw"),
                "p99_ms": (s3g.get("get_open_loop") or {}).get("p99_ms"),
                "identical": s3g.get("gateway_direct_identical"),
                "note": "open-loop zipf(1.1) GetObject through the S3 "
                "fast tier at the same-credit-window inline ping rate "
                "(CO-corrected p50/p99/p999 in s3.put_qps detail); "
                "vs_baseline = get_qps / raw_get_qps (direct volume "
                "GETs, same window); identical = gateway GETs "
                "byte-identical to direct volume chunk reads",
            }
        )
        extra.append(
            {
                "metric": "s3.list_qps",
                "value": s3g.get("list_qps"),
                "unit": "#/sec",
                "vs_baseline": s3g.get("list_scanned_per_request"),
                "scan_bounded": s3g.get("list_scan_bounded"),
                "note": "ListObjectsV2 pages (max-keys=100) walked via "
                "continuation tokens over a "
                f"{s3g.get('list_keys')}-key bucket (>= 100x the page); "
                "vs_baseline = store entries SCANNED per request — the "
                "range-scan bound O(max-keys + CommonPrefixes), not "
                "O(bucket); scan_bounded asserts it; full-walk "
                "concatenation checked against the sorted key set "
                "(list_walk_complete in s3.put_qps detail)",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "s3.put_qps", "error": str(e)[:200]})

    try:
        if not budgeted("lifecycle.convergence", 45):
            raise _Skip()
        lc = measure_lifecycle_convergence(
            n_cold_volumes=int(os.environ.get("BENCH_LC_VOLUMES", 4)),
        )
        extra.append(
            {
                "metric": "lifecycle.convergence",
                "value": lc.get("conversions_ec_ok"),
                "unit": "# conversions",
                # acceptance ratio: foreground read p99 WITH conversions
                # in flight over the no-conversion window (target <= 1.5)
                "vs_baseline": lc.get("fg_p99_ratio"),
                "converged": lc.get("converted_all"),
                "identical": lc.get("byte_identical"),
                "queue_depth_end": lc.get("lifecycle_queue_depth_end"),
                "detail": lc,
                "note": "lifecycle plane (ISSUE 10): cold collection "
                "auto-EC'd by the master planner while an open-loop "
                "zipf(1.1) foreground read stream runs at a fraction of "
                "the same-credit-window inline ping; value = completed "
                "hot→warm conversions, vs_baseline = foreground p99 "
                "with/without conversions in flight (the arxiv "
                "1709.05365 contention check, bounded by the shared "
                "MaintenanceBudget plane=lifecycle + pressure yielding; "
                "acceptance <= 1.5); identical = every converted object "
                "read back byte-identical through the EC path; "
                "queue_depth_end asserts the planner drained",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append(
            {"metric": "lifecycle.convergence", "error": str(e)[:200]}
        )

    try:
        if not budgeted("lifecycle.cold_tier", 45):
            raise _Skip()
        ct = measure_cold_tier(
            n_cold_volumes=int(os.environ.get("BENCH_CT_VOLUMES", 2)),
        )
        extra.append(
            {
                "metric": "lifecycle.cold_tier",
                "value": ct.get("recall_p99_ms"),
                "unit": "ms recall p99",
                # acceptance ratio: foreground read p99 WITH the cold-tier
                # arc in flight over the quiet window (target <= 1.5)
                "vs_baseline": ct.get("fg_p99_ratio"),
                "cache_hit_rate": ct.get("cache_hit_rate"),
                "identical": ct.get("byte_identical"),
                "queue_depth_end": ct.get("lifecycle_queue_depth_end"),
                "detail": ct,
                "note": "cold-tier plane (ISSUE 14): cold collection "
                "auto-EC'd, shard files offloaded to the in-tree HTTP "
                "blob server (ServingCore-fronted), read back through "
                "the byte-range read-through cache, then recalled on "
                "heat — all UNDER an open-loop zipf(1.1) foreground "
                "read stream at a fraction of the same-credit-window "
                "inline ping; value = per-holder recall wall p99, "
                "vs_baseline = foreground p99 with/without the arc "
                "(arxiv 1709.05365 contention check, bounded by "
                "plane=lifecycle MaintenanceBudget + pressure yielding; "
                "acceptance <= 1.5); identical = byte identity at EVERY "
                "stage (EC'd / offloaded / cache-served / recalled); "
                "cache_hit_rate over the offloaded read passes",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append(
            {"metric": "lifecycle.cold_tier", "error": str(e)[:200]}
        )

    try:
        if not budgeted("qos.fairness", 60):
            raise _Skip()
        qf = measure_qos_fairness(
            num_files=int(os.environ.get("BENCH_QOS_FILES", 300)),
        )
        extra.append(
            {
                "metric": "qos.fairness",
                "value": qf.get("victim_p99_contended_ms"),
                "unit": "ms p99",
                # acceptance ratio: victim p99 with a 3x-share zipf
                # aggressor over its SOLO p99 (target <= 2.0)
                "vs_baseline": qf.get("victim_p99_over_solo"),
                "quota_sheds": qf.get("quota_sheds"),
                "quota_shed_path_us": qf.get("quota_shed_path_us"),
                "victim_goodput_qps": (
                    qf.get("victim_contended") or {}
                ).get("goodput_qps"),
                "detail": qf,
                "note": "tenant QoS plane (ISSUE 12): an aggressive "
                "zipf(1.2) tenant offering 3x its fair share (share = "
                "ceiling x util / 2, util disclosed; rate quota set AT "
                "the share) runs concurrently with a well-behaved "
                "tenant at its share; value = victim p99 under attack, "
                "vs_baseline = that p99 over the victim's solo run — "
                "both SERVER-side per-tenant admitted latency (wait + "
                "service from the gate's log buckets; under a "
                "saturated shared-loop generator the client RTT "
                "records the generator's own backlog — RTT p99s "
                "disclosed alongside as victim_rtt_p99_*; acceptance "
                "<= 2x); the aggressor's overage sheds reason=quota "
                "at quota_shed_path_us (in-situ µs microbench) with "
                "Retry-After, counted per (class,reason,tenant); "
                "client breakers disabled like serving.overload (the "
                "generator must keep offering)",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "qos.fairness", "error": str(e)[:200]})

    try:
        if not budgeted("soak.multi_tenant", 180):
            raise _Skip()
        sk = measure_multitenant_soak(
            total_keys=int(
                os.environ.get("BENCH_SOAK_KEYS", 1_000_000)
            ),
            tenants=int(os.environ.get("BENCH_SOAK_TENANTS", 8)),
            time_cap_s=min(420.0, max(120.0, remaining() - 60.0)),
        )
        extra.append(
            {
                "metric": "soak.multi_tenant",
                "value": sk.get("keys_written"),
                "unit": "# keys",
                # acceptance ratio: max/min per-tenant read goodput
                # under the clamped admission limit (1.0 = perfectly
                # fair; target close to 1)
                "vs_baseline": sk.get("fairness_ratio"),
                "identity_violations": sk.get("identity_violations"),
                "raw_write_qps": sk.get("raw_write_qps"),
                "read_goodput_qps": sk.get("read_goodput_qps"),
                "tenant_label_cardinality": sk.get(
                    "tenant_label_cardinality"
                ),
                "time_capped": sk.get("time_capped"),
                "detail": sk,
                "note": "tenant QoS soak (ISSUE 12): value = keys "
                "written across >= 8 tenants through BOTH tiers (raw "
                "volume tier via batched fast-tier frames with "
                "X-Seaweed-Tenant attribution; S3 tier via per-tenant "
                "V4-signed PUT/GETs against per-identity buckets), one "
                "credit window; vs_baseline = fairness ratio (max/min "
                "per-tenant goodput) during a concurrent all-tenant "
                "read window under a CLAMPED admission limit so the "
                "DRR dequeue orders service; identity_violations "
                "counts reads whose bytes differ from the reading "
                "tenant's own deterministic corpus (acceptance: 0); "
                "tenant metric label values stay top-K-bounded "
                "(tenant_label_cardinality; the tier-1 metrics lint "
                "enforces the cap); time_capped discloses when the "
                "write phase hit its wall cap short of the 1M target",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "soak.multi_tenant", "error": str(e)[:200]})

    try:
        if not budgeted("soak.production", 240):
            raise _Skip()
        pk = measure_production_soak(
            total_keys=int(
                os.environ.get("BENCH_PROD_SOAK_KEYS", 10_000_000)
            ),
            tenants=int(os.environ.get("BENCH_PROD_SOAK_TENANTS", 16)),
            volumes=int(os.environ.get("BENCH_PROD_SOAK_VOLUMES", 3)),
            soak_window_s=float(
                os.environ.get("BENCH_PROD_SOAK_WINDOW_S", 60.0)
            ),
            time_cap_s=min(540.0, max(180.0, remaining() - 90.0)),
        )
        slo = pk.get("slo", {})
        extra.append(
            {
                "metric": "soak.production",
                "value": pk.get("goodput_over_offered"),
                "unit": "goodput/offered",
                "vs_baseline": 1.0 if slo.get("pass") else 0.0,
                "keys_written": pk.get("keys_written"),
                "process_faults_fired": pk.get("process_faults_fired"),
                "sigkill_recovered": pk.get("sigkill_recovered"),
                "identity_violations": pk.get("identity_violations"),
                "isolation_violations": pk.get("isolation_violations"),
                "queues_drained": pk.get("queues_drained"),
                "schedule_reproducible": pk.get(
                    "schedule_reproducible"
                ),
                "fg_p99_ms": pk.get("fg_p99_ms"),
                "bloom": pk.get("bloom"),
                "time_capped": pk.get("time_capped"),
                "detail": pk,
                "note": "production chaos soak (ISSUE 16 tentpole): ONE "
                "sustained SLO-scored run over a REAL multi-process "
                "cluster (master + volume fleet + filer fleet + S3 "
                "gateway + blob cold tier, each its own OS process via "
                "ops/proc_cluster) with ALL background planes live "
                "(repair, vacuum, lifecycle/cold tier, scrub) while a "
                "SEEDED process-fault schedule SIGKILLs+respawns and "
                "SIGSTOPs volume servers and hard-kills a filer; value "
                "= goodput/offered during the chaos window (open-loop "
                "zipf, CO-corrected percentiles); vs_baseline = 1 only "
                "if EVERY SLO term holds: goodput floor, fg p99 "
                "ceiling, ZERO byte-identity violations, ZERO "
                "tenant-isolation violations (cross-tenant signed GETs "
                "denied by bucket-scoped IAM), all maintenance queues "
                "drained at quiesce, >= 2 process faults fired with "
                "SIGKILL recovery, and the fault schedule regenerates "
                "bit-identically from its seed; detail.bloom is the "
                "per-run LSM bloom consultation tail scraped from each "
                "volume process's /debug/needle_map",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "soak.production", "error": str(e)[:200]})

    try:
        if not budgeted("soak.geo", 150):
            raise _Skip()
        gk = measure_geo_soak(
            pre_files=int(os.environ.get("BENCH_GEO_PRE_FILES", 30)),
            during_files=int(
                os.environ.get("BENCH_GEO_DURING_FILES", 30)
            ),
            post_files=int(os.environ.get("BENCH_GEO_POST_FILES", 15)),
            partition_duration_s=float(
                os.environ.get("BENCH_GEO_PARTITION_S", 8.0)
            ),
            time_cap_s=min(240.0, max(120.0, remaining() - 60.0)),
        )
        gslo = gk.get("slo", {})
        extra.append(
            {
                "metric": "geo.replication_lag",
                "value": gk.get("lag_p99_s"),
                "unit": "seconds (p99)",
                "vs_baseline": None,
                "detail": {
                    k: gk.get(k)
                    for k in (
                        "max_lag_s",
                        "post_heal_lag_s",
                        "applied",
                        "skipped",
                        "retried",
                        "partition",
                    )
                },
                "note": "cross-DC async replication lag p99 from the "
                "second site's GeoStatus histogram (event-ts to "
                "applied-on-peer), measured across the SAME run as "
                "soak.geo — the tail includes the WAN-partition window, "
                "so it is an upper bound on steady-state lag",
            }
        )
        extra.append(
            {
                "metric": "soak.geo",
                "value": gk.get("files_written"),
                "unit": "files replicated cross-DC",
                "vs_baseline": 1.0 if gslo.get("pass") else 0.0,
                "partition_observed": gk.get("partition_observed"),
                "missing_on_peer": gk.get("missing_on_peer"),
                "extra_on_peer": gk.get("extra_on_peer"),
                "byte_mismatches": gk.get("byte_mismatches"),
                "primary_read_p99_ms": gk.get("primary_read_p99_ms"),
                "time_capped": gk.get("time_capped"),
                "detail": gk,
                "note": "two-site geo soak (ISSUE 19 tentpole): TWO real "
                "multi-process clusters in dc-a/dc-b, the second site's "
                "filer tailing the primary's durable meta-log "
                "(-geoSource) and shipping chunk bytes, with a windowed "
                "WAN partition (wan_partition_plan on the second site's "
                "filer child: every primary listen address, HTTP + gRPC "
                "twins) cutting the link mid-run; value = files written "
                "on the primary, all byte-verified on the peer after "
                "heal; vs_baseline = 1 only if EVERY SLO term holds: "
                "primary writes never failed during the cut, the "
                "partition was actually observed (disconnect or lag >= "
                "half the window), post-heal lag drained under bound, "
                "ZERO lost and ZERO duplicated mutations (namespace "
                "diff: no missing/extra/mismatched files on the peer — "
                "split-brain would surface as extra or mismatch), "
                "primary same-DC read p99 held THROUGH the partition, "
                "and no full-resync was required (cursor resumed "
                "exactly)",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "soak.geo", "error": str(e)[:200]})

    try:
        if not budgeted("serving_write_budget", 25):
            raise _Skip()
        wb = measure_write_budget(serving=serving_qps, ping=ping_detail)
        extra.append(
            {
                "metric": "serving_write_budget",
                "value": wb["component_sum_us"],
                "unit": "us (component sum)",
                "vs_baseline": wb.get("coverage_of_p50"),
                "detail": wb,
                "note": "itemized write-path budget (ISSUE 2 tentpole): "
                "value = the client-partitioned leg sum measured in the "
                "same c=16 run as the serving p50; vs_baseline = share "
                "of the measured write p50 those components explain "
                "(acceptance: >= 0.8). detail carries unit CPU costs "
                "per handler component and the fsync tier's group-commit "
                "flush wait",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "serving_write_budget", "error": str(e)[:200]})

    try:
        if not budgeted("ec.encode_throughput.geometries", 90):
            raise _Skip()
        geo = measure_geometries()
        extra.append(
            {
                "metric": "ec.encode_throughput.geometries",
                "value": geo,
                "unit": "GB/s",
                "note": "kernel encode at alternate RS geometries "
                "(BASELINE config 5); 10.4 is the headline metric",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append(
            {"metric": "ec.encode_throughput.geometries", "error": str(e)[:200]}
        )

    try:
        if not budgeted("ec.encode.multi", 60):
            raise _Skip()
        m = measure_multi_encode(
            n_volumes=int(os.environ.get("BENCH_MULTI_VOLS", 8)),
            vol_bytes=int(os.environ.get("BENCH_MULTI_MB", 32)) << 20,
        )
        extra.append(
            {
                "metric": "ec.encode.multi",
                "value": round(m["multi_gbps"], 3),
                "unit": "GB/s",
                # vs the same volumes encoded one at a time, same codec
                "vs_baseline": round(m["multi_gbps"] / m["seq_gbps"], 2),
                "detail": m,
                "note": f"{m['n_volumes']} volumes encoded concurrently "
                "(write_ec_files_multi) vs sequentially, adaptive codec. "
                f"DISCLOSURE, not a target: host_cpus={available_cpus()} "
                "— host-side parallel speedup is structurally capped at "
                "~1.0x on a 1-core host; BASELINE config 3's multi-volume "
                "number is the DEVICE batch dimension "
                "(ec.encode.multi.device)",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append({"metric": "ec.encode.multi", "error": str(e)[:200]})

    try:
        if not budgeted("ec.encode.multi.device", 60):
            raise _Skip()
        md = measure_multi_device(
            n_volumes=int(os.environ.get("BENCH_MULTI_DEV_VOLS", 64))
        )
        # the batching win must HOLD AS V GROWS (VERDICT r4 item 7): a
        # second shape with 4x the volume count, still launch-bound
        try:
            if remaining() > 45:
                md_big = measure_multi_device(
                    n_volumes=int(
                        os.environ.get("BENCH_MULTI_DEV_VOLS_BIG", 256)
                    ),
                    k_lo=4,
                    k_hi=16,
                )
                md["v256"] = {
                    k: md_big[k]
                    for k in (
                        "n_volumes",
                        "bytes",
                        "wide_gbps",
                        "per_volume_dispatch_gbps",
                        "batch_speedup",
                    )
                }
        except Exception as e:
            md["v256"] = {"error": str(e)[:120]}
        extra.append(
            {
                "metric": "ec.encode.multi.device",
                "value": md["wide_gbps"],
                "unit": "GB/s",
                # the batch dimension's win: one wide dispatch vs V
                # per-volume dispatches of the same kernel. THIS is
                # BASELINE config 3's multi-volume number (the host
                # ec.encode.multi leg is a 1-core disclosure)
                "vs_baseline": md["batch_speedup"],
                "detail": md,
                "note": f"{md['n_volumes']} small volumes as ONE wide "
                "[10, V*W] device dispatch vs per-volume dispatches "
                "(BASELINE config 3's batch dimension in the launch-bound "
                "small-volume regime; HBM-resident, slope-timed; at "
                ">=20MB/dispatch batching is ~1x because launches already "
                "amortize); detail.v256 shows the win holding at 4x the "
                "volume count",
            }
        )
    except _Skip:
        pass
    except Exception as e:
        extra.append(
            {"metric": "ec.encode.multi.device", "error": str(e)[:200]}
        )

    if budgeted("ec.encode.e2e", 45):
        extra.extend(_run_e2e_timeboxed(time_left=remaining()))
    else:
        extra.append(
            {"metric": "ec.encode.e2e.best", "skipped": "bench budget spent"}
        )

    _emit_final(partial)


def _device_status() -> str:
    """Machine-readable provenance for the device legs: 'tpu' only when
    the real accelerator answered; anything else marks a stand-in run.
    Round 4's artifact was a CPU stand-in with no way to tell — this field
    is the fix (VERDICT r4 item 1b)."""
    if os.environ.get("GRAFT_BENCH_CPU_FALLBACK"):
        return "cpu_standin"
    try:
        import jax

        return jax.devices()[0].platform  # "tpu" / "cpu" / ...
    except Exception:
        return "unknown"


# keys worth carrying on the compact final line, in emission order
_COMPACT_KEYS = (
    "metric",
    "value",
    "unit",
    "vs_baseline",
    "write_qps",
    "write_vs_baseline",
    "read_p99_ms",
    "p99_ms",
    "p999_ms",
    "coverage_of_p50",
    "identical",
    "scan_bounded",
    "skipped",
)
_FINAL_LINE_CAP = 1900  # bytes; the driver tail-captures 2,000 chars


def _compact_entry(e: dict) -> dict:
    c = {k: e[k] for k in _COMPACT_KEYS if k in e}
    if "error" in e:
        c["error"] = str(e["error"])[:60]
    # dict-valued metrics (geometries, rooflines): keep numbers, drop prose
    v = c.get("value")
    if isinstance(v, dict):
        c["value"] = {
            k: (round(x, 3) if isinstance(x, float) else x)
            for k, x in v.items()
            if isinstance(x, (int, float))
        }
    return c


_EMIT_LOCK = threading.Lock()
_EMITTED = False
_LAST_HEADLINE: dict = {}  # main()'s in-progress record, for crash paths


def _arm_watchdog(deadline_s: float, partial: dict) -> None:
    """Emit `partial` and hard-exit if the bench is still running at the
    deadline — a tunnel death mid-jax-call is an uninterruptible hang that
    would otherwise lose every measured number to the driver's kill."""

    def fire():
        time.sleep(deadline_s)

        def add_marker():
            # runs under _EMIT_LOCK inside _emit_final: a run completing
            # right at the deadline must neither gain a spurious
            # watchdog-error entry nor see the shared dict mutated while
            # the winning emitter is serializing it
            partial.setdefault("extra", []).append(
                {
                    "metric": "watchdog",
                    "error": "bench exceeded budget+150s (device hang?); "
                    "partial results emitted",
                }
            )

        # only kill the process if WE emitted: a normal completion that
        # already printed (or is printing — _emit_final waits on the
        # lock) must exit normally, never be os._exit'd mid-write
        if _emit_final(partial, mutate=add_marker):
            sys.stdout.flush()
            os._exit(3)

    threading.Thread(target=fire, daemon=True).start()


def _emit_final(headline: dict, mutate=None) -> bool:
    """Write the full result to BENCH_DETAIL.json and print ONE compact
    JSON line guaranteed under the driver's 2,000-char tail capture.
    Once per process and fully under the lock, so a concurrent caller
    (the watchdog) can neither interleave a second line nor observe a
    half-finished emission; -> True when THIS call did the emitting.
    `mutate`, when given, runs under the lock only if this call wins —
    the watchdog's error marker must not land on a completed run.

    Round 4's official record was `parsed: null` because the single output
    line grew past the capture window; the detail file is now the deep
    record and the stdout line is the contract-sized summary."""
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        if mutate is not None:
            mutate()
        _append_device_history(headline)
        # serialize from a snapshot: the lock excludes other EMITTERS, not
        # main()'s appends to the live dict — a watchdog firing mid-run
        # must not json.dump a dict that mutates under it
        import copy as _copy

        headline = _copy.deepcopy(headline)
        detail_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json"
        )
        try:
            with open(detail_path, "w") as f:
                json.dump(headline, f, indent=1)
                f.write("\n")
        except Exception as e:  # unwritable detail must not kill stdout
            print(
                f"bench: BENCH_DETAIL.json not written: {e}", file=sys.stderr
            )

        compact = {k: v for k, v in headline.items() if k != "extra"}
        compact.pop("note", None)
        # the inline history rides the detail file only; the compact line
        # keeps the pointer
        compact.pop("device_history", None)
        compact["detail_file"] = "BENCH_DETAIL.json"
        extras = [_compact_entry(e) for e in headline.get("extra", [])]
        compact["extra"] = extras
        line = json.dumps(compact, separators=(",", ":"))
        # degrade gracefully if some future metric bloats the line: drop
        # skipped markers first, then trim trailing extras — both degrade
        # steps flag the omission so the record never silently shrinks
        if len(line) > _FINAL_LINE_CAP:
            extras = [e for e in extras if "skipped" not in e]
            compact["extra"] = extras
            compact["extra_truncated"] = True
            line = json.dumps(compact, separators=(",", ":"))
        while len(line) > _FINAL_LINE_CAP and extras:
            extras.pop()
            compact["extra_truncated"] = True
            line = json.dumps(compact, separators=(",", ":"))
        print(line, flush=True)
        # claim the emission only once the compact line is actually out:
        # if anything above raised, the flag stays False and the OTHER
        # caller (normal completion vs watchdog) still prints the artifact
        _EMITTED = True
        return True


def _append_device_history(headline: dict) -> None:
    """Append {run, device_status} to DEVICE_HISTORY.jsonl next to
    bench.py (ISSUE 6 satellite / ROADMAP device-story item): device legs
    keep degrading to `cpu_standin` when the relay is down, and without a
    persisted history each such run silently overwrites the only evidence
    that r01-r03 DID reach the device. `run` is the 1-based line count;
    the headline gains a `device_history` pointer + the trailing entries
    so the detail file shows the availability trend inline. Best-effort:
    an unwritable history must never cost the bench artifact."""
    try:
        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "DEVICE_HISTORY.jsonl",
        )
        text = ""
        if os.path.exists(path):
            with open(path) as f:
                text = f.read()
        lines = [ln for ln in text.splitlines() if ln.strip()]
        # run numbering counts lines without parsing, and the inline tail
        # parses tolerantly: one torn line (watchdog kill mid-append,
        # disk-full truncation) must not disable the feature forever
        prior = []
        for ln in lines[-7:]:
            try:
                prior.append(json.loads(ln))
            except (json.JSONDecodeError, ValueError):
                continue
        entry = {
            "run": len(lines) + 1,
            "device_status": headline.get("device_status", "unknown"),
            "headline_gbps": headline.get("value"),
        }
        # per-LEG device status (ISSUE 17 satellite): the run-level status
        # says what the headline kernel saw, but individual legs can land
        # on different executors (mesh legs forced to virtual host
        # devices, e2e on the stand-in, mxu skipped) — record each leg
        # that disclosed its own status so 65 GB/s-era numbers stay
        # comparable per-metric when the relay returns
        legs = {}
        for e in headline.get("extra") or []:
            if (
                isinstance(e, dict)
                and e.get("metric")
                and "device_status" in e
            ):
                legs[e["metric"]] = e["device_status"]
        if legs:
            entry["legs"] = legs
        with open(path, "a") as f:
            if text and not text.endswith("\n"):
                f.write("\n")  # a torn tail must not absorb this entry
            f.write(json.dumps(entry, separators=(",", ":")) + "\n")
        headline["device_history_file"] = "DEVICE_HISTORY.jsonl"
        headline["device_history"] = prior + [entry]
    except Exception as e:
        print(f"bench: DEVICE_HISTORY.jsonl not written: {e}", file=sys.stderr)


def _probe_device_backend(timeout: float = 120.0) -> str:
    """Shared out-of-process probe (util/device_probe.py): the tunneled
    backend can HANG (not raise) at init when its relay is down — observed
    live — and a hung bench records nothing at all. Three-state verdict:
    "ok" / "down" / "timeout" (hung to deadline = hard-down relay)."""
    from seaweedfs_tpu.util.device_probe import probe_device_backend

    return probe_device_backend(timeout=timeout)[0]


def _device_backend_usable_with_retry() -> bool:
    """The tunnel FLAPS (observed across rounds 3-4): a single failed probe
    at bench time turned round 4's official device legs into CPU stand-ins.
    Retry with backoff before giving up (VERDICT r4 item 1b).

    The per-probe deadline stays generous (150s: cold jax init over the
    tunnel legitimately takes ~2 min, and shrinking it would demote a
    slow-but-healthy backend to a stand-in), but a probe that HUNG to its
    deadline is a hard-down relay — retrying would burn another 150s for
    nothing and starve the bench body of driver wall-clock, so only
    fast-fails (relay up, backend erroring) are retried."""
    delays = (15.0, 30.0)  # between attempts; fast-fail probes ~seconds
    for attempt in range(len(delays) + 1):
        verdict = _probe_device_backend(timeout=150.0)
        if verdict == "ok":
            if attempt:
                print(
                    f"bench: device probe recovered on attempt "
                    f"{attempt + 1}",
                    file=sys.stderr,
                    flush=True,
                )
            return True
        if verdict == "timeout":
            print(
                "bench: device probe HUNG to its 150s deadline "
                "(hard-down relay); not retrying",
                file=sys.stderr,
                flush=True,
            )
            return False
        if attempt < len(delays):
            print(
                f"bench: device probe failed (attempt {attempt + 1}/"
                f"{len(delays) + 1}); retrying in {delays[attempt]:.0f}s",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(delays[attempt])
    return False


if __name__ == "__main__":
    if (
        not os.environ.get("GRAFT_BENCH_CPU_FALLBACK")
        and not _device_backend_usable_with_retry()
    ):
        # the device is unreachable: losing the WHOLE bench to a hang would
        # record nothing — re-exec onto pure CPU (axon hook disarmed) so
        # the host-side numbers (serving, e2e, host kernel, multi) still
        # land; device-kernel legs then honestly measure the CPU stand-in
        print(
            "bench: device backend unusable (probe failed/hung); "
            "re-exec on pure CPU — device legs are CPU stand-ins this run",
            file=sys.stderr,
            flush=True,
        )
        env = dict(os.environ)
        env["GRAFT_BENCH_CPU_FALLBACK"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        os.execve(sys.executable, [sys.executable, *sys.argv], env)
    try:
        sys.exit(main())
    except SystemExit:
        raise
    except BaseException as e:
        # the RAISING mid-run failure mode (tunnel dies, jax raises from a
        # headline leg): still emit whatever was measured so the
        # driver-visible artifact survives (the watchdog covers the
        # HANGING mode)
        import traceback

        traceback.print_exc()
        head = _LAST_HEADLINE
        head.setdefault("metric", "ec.encode_throughput")
        head.setdefault("value", None)
        head.setdefault("unit", "GB/s")
        head.setdefault("vs_baseline", None)
        head.setdefault("device_status", "unknown")
        head.setdefault("extra", []).append(
            {"metric": "bench_main", "error": repr(e)[:200]}
        )
        _emit_final(head)
        sys.exit(1)
