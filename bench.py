"""North-star benchmark: RS(10,4) ec.encode throughput on TPU vs CPU baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

- TPU number: steady-state Pallas GF(2^8) encode over HBM-resident packed
  stripe batches (the BASELINE.json batched-multi-volume configuration).
  Timing uses K-run slope with a host digest pull per measurement, because
  block_until_ready on tunneled backends can return before execution
  completes — the slope between K=4 and K=20 cancels the constant RTT.
- CPU baseline: the same encode via the native C++ SSSE3 PSHUFB kernel,
  single-threaded — the same technique as the reference's
  klauspost/reedsolomon pipeline (ref: ec_encoder.go:120-136; BASELINE.md
  notes the reference publishes no ec.encode number, so we measure the
  strongest honest equivalent on this host). Falls back to the numpy table
  path when no C++ toolchain is available.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def measure_cpu_baseline(codec, data: np.ndarray, min_seconds: float = 1.0) -> float:
    """GB/s of data encoded by the numpy single-thread path."""
    codec.encode(data[:, : 1 << 16])  # warm tables
    n_bytes = data.size
    iters = 0
    t0 = time.perf_counter()
    while True:
        codec.encode(data)
        iters += 1
        dt = time.perf_counter() - t0
        if dt >= min_seconds and iters >= 2:
            return n_bytes * iters / dt / 1e9


def measure_tpu(parity_matrix, packed_np: np.ndarray) -> float:
    """GB/s of data encoded on device (slope-timed)."""
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.gf256 import gf_matmul_packed

    packed = jax.device_put(jnp.asarray(packed_np))
    n_bytes = packed_np.size * 4

    encode = jax.jit(lambda p: gf_matmul_packed(parity_matrix, p))
    digest = jax.jit(lambda x: x.sum(dtype=jnp.uint32))

    _ = np.asarray(digest(encode(packed)))  # compile + warm

    def run(k: int) -> float:
        t0 = time.perf_counter()
        out = None
        for _ in range(k):
            out = encode(packed)
        _ = np.asarray(digest(out))  # forces the whole FIFO queue to drain
        return time.perf_counter() - t0

    run(2)  # warm the pull path
    k_lo, k_hi = 8, 64
    t_lo = min(run(k_lo) for _ in range(5))
    t_hi = min(run(k_hi) for _ in range(5))
    per_iter = (t_hi - t_lo) / (k_hi - k_lo)
    if per_iter <= 0:  # RTT noise swamped the slope; fall back to bulk timing
        per_iter = t_hi / k_hi
    return n_bytes / per_iter / 1e9


def main() -> None:
    from seaweedfs_tpu.ops.gf256 import pack_bytes_host
    from seaweedfs_tpu.storage.erasure_coding.coder_cpu import CpuRSCodec
    from seaweedfs_tpu.tpu.coder import get_codec

    codec = CpuRSCodec()
    rng = np.random.default_rng(0)

    # CPU baseline: native SIMD single-thread on a 40MB stripe batch
    baseline_codec = get_codec("cpu")
    cpu_data = rng.integers(0, 256, size=(10, 4 << 20), dtype=np.uint8)
    cpu_gbps = measure_cpu_baseline(baseline_codec, cpu_data)

    # TPU on a 160MB HBM-resident stripe batch
    data = rng.integers(0, 256, size=(10, 16 << 20), dtype=np.uint8)
    packed = pack_bytes_host(data)
    tpu_gbps = measure_tpu(codec.parity_matrix, packed)

    print(
        json.dumps(
            {
                "metric": "ec.encode_throughput",
                "value": round(tpu_gbps, 3),
                "unit": "GB/s",
                "vs_baseline": round(tpu_gbps / cpu_gbps, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
